"""Pooling functionals via ``lax.reduce_window``.

Reference: `python/paddle/nn/functional/pooling.py`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor.registry import defop

__all__ = ["max_pool1d", "max_pool2d", "max_pool3d",
           "avg_pool1d", "avg_pool2d", "avg_pool3d",
           "adaptive_avg_pool1d", "adaptive_avg_pool2d", "adaptive_avg_pool3d",
           "adaptive_max_pool1d", "adaptive_max_pool2d", "adaptive_max_pool3d",
           "max_pool2d_with_index", "max_pool3d_with_index",
           "fractional_max_pool2d", "fractional_max_pool3d",
           "max_unpool1d", "max_unpool2d", "max_unpool3d", "pool2d", "pool3d"]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(e) for e in v)
    return (int(v),) * n


def _pool_pad(padding, nd):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if all(isinstance(p, int) for p in padding):
        if len(padding) == nd:
            return [(p, p) for p in padding]
        if len(padding) == 2 * nd:
            return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    return [tuple(int(e) for e in p) for p in padding]


def _reduce_init(reduce_fn, dtype):
    """Identity element for a reduce_window monoid, as a Python/numpy
    scalar — array-wrapped inits defeat JAX's monoid recognition and lose
    the op's autodiff rule under jit."""
    if reduce_fn is jax.lax.add:
        return 0.0
    if jnp.issubdtype(dtype, jnp.floating):
        return float("-inf")
    return np.dtype(dtype).type(jnp.iinfo(dtype).min)


def _reduce_pool(x, kernel, stride, padding, nd, channel_last, init, op,
                 ceil_mode=False):
    k = _tuple(kernel, nd)
    s = _tuple(stride if stride is not None else kernel, nd)
    p = _pool_pad(padding, nd)
    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pads = ([(0, 0)] + p + [(0, 0)]) if isinstance(p, list) else p
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        pads = ([(0, 0), (0, 0)] + p) if isinstance(p, list) else p
    # init must stay a Python scalar: JAX recognizes the (init, op) monoid
    # (sum/max/min) only for literal identities — wrapping it in an array
    # defeats the detection and the op loses its autodiff rule under jit.
    if isinstance(pads, list) and ceil_mode:
        # grow right-pad so the last partial window is included
        spatial = x.shape[1:-1] if channel_last else x.shape[2:]
        base = 1 if channel_last else 2
        pads = list(pads)
        for i in range(nd):
            size = spatial[i] + pads[base + i][0] + pads[base + i][1]
            rem = (size - k[i]) % s[i]
            if rem != 0:
                lo, hi = pads[base + i]
                pads[base + i] = (lo, hi + (s[i] - rem))
    return jax.lax.reduce_window(x, init, op, window, strides, pads), \
        (window, strides, pads)


def _is_channel_last(data_format):
    """One classification shared by the pooling dispatch and the
    return_mask guards, so an accepted alias can't drift between them."""
    return data_format in ("NHWC", "NWC", "NDHWC", "NLC")


def _max_pool(x, kernel, stride, padding, nd, data_format, ceil_mode):
    channel_last = _is_channel_last(data_format)
    neg = _reduce_init(jax.lax.max, x.dtype)
    out, _ = _reduce_pool(x, kernel, stride, padding, nd, channel_last,
                          neg, jax.lax.max, ceil_mode)
    return out


def _avg_pool(x, kernel, stride, padding, nd, data_format, exclusive,
              ceil_mode):
    channel_last = _is_channel_last(data_format)
    summed, (window, strides, pads) = _reduce_pool(
        x, kernel, stride, padding, nd, channel_last, 0.0, jax.lax.add,
        ceil_mode)
    if exclusive and not isinstance(pads, str):
        ones = jnp.ones(x.shape, dtype=x.dtype)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                       window, strides, pads)
        return summed / counts
    return summed / float(np.prod(_tuple(kernel, nd)))


@defop()
def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCL"):
    if return_mask:
        if _is_channel_last(data_format):
            raise ValueError(
                "max_pool1d(return_mask=True) requires data_format='NCL'; "
                f"got {data_format!r} (the mask path pools channel-first "
                "axes)")
        k = _tuple(kernel_size, 1)
        st = _tuple(stride, 1) if stride is not None else k
        dims = _fixed_window_dims(x.shape[2:], k, st, _tuple(padding, 1),
                                  ceil_mode)
        return _windowed_max(x, dims, True)
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _max_pool(x, kernel_size, stride, padding, 1, fmt, ceil_mode)


@defop()
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW"):
    if return_mask:
        if _is_channel_last(data_format):
            raise ValueError(
                "max_pool2d(return_mask=True) requires data_format="
                f"'NCHW'; got {data_format!r} (the mask path pools "
                "channel-first axes)")
        k = _tuple(kernel_size, 2)
        st = _tuple(stride, 2) if stride is not None else k
        dims = _fixed_window_dims(x.shape[2:], k, st, _tuple(padding, 2),
                                  ceil_mode)
        return _windowed_max(x, dims, True)
    return _max_pool(x, kernel_size, stride, padding, 2, data_format,
                     ceil_mode)


@defop()
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW"):
    if return_mask:
        if _is_channel_last(data_format):
            raise ValueError(
                "max_pool3d(return_mask=True) requires data_format="
                f"'NCDHW'; got {data_format!r} (the mask path pools "
                "channel-first axes)")
        k = _tuple(kernel_size, 3)
        st = _tuple(stride, 3) if stride is not None else k
        dims = _fixed_window_dims(x.shape[2:], k, st, _tuple(padding, 3),
                                  ceil_mode)
        return _windowed_max(x, dims, True)
    return _max_pool(x, kernel_size, stride, padding, 3, data_format,
                     ceil_mode)


@defop()
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL"):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _avg_pool(x, kernel_size, stride, padding, 1, fmt, exclusive,
                     ceil_mode)


@defop()
def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCHW"):
    return _avg_pool(x, kernel_size, stride, padding, 2, data_format,
                     exclusive, ceil_mode)


@defop()
def avg_pool3d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, divisor_override=None, data_format="NCDHW"):
    return _avg_pool(x, kernel_size, stride, padding, 3, data_format,
                     exclusive, ceil_mode)


def _adaptive_windows(in_size, out_size):
    """start/end indices per output cell, paddle/torch adaptive convention."""
    starts = [int(np.floor(i * in_size / out_size)) for i in range(out_size)]
    ends = [int(np.ceil((i + 1) * in_size / out_size)) for i in range(out_size)]
    return starts, ends


def _adaptive_pool(x, output_size, nd, data_format, reduce_fn):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    out_sizes = _tuple(output_size, nd)
    spatial_base = 1 if channel_last else 2
    # uniform case lowers to one strided reduce_window (fast path)
    in_sizes = x.shape[spatial_base:spatial_base + nd]
    if all(i % o == 0 for i, o in zip(in_sizes, out_sizes)):
        k = tuple(i // o for i, o in zip(in_sizes, out_sizes))
        if channel_last:
            window = (1,) + k + (1,)
        else:
            window = (1, 1) + k
        init = _reduce_init(reduce_fn, x.dtype)
        out = jax.lax.reduce_window(x, init, reduce_fn, window, window,
                                    "VALID")
        if reduce_fn is jax.lax.add:
            out = out / float(np.prod(k))
        return out
    # general case: gather per-cell slices (static loop, still one XLA graph)
    for d in range(nd):
        axis = spatial_base + d
        starts, ends = _adaptive_windows(x.shape[axis], out_sizes[d])
        pieces = []
        for s, e in zip(starts, ends):
            sl = jax.lax.slice_in_dim(x, s, e, axis=axis)
            if reduce_fn is jax.lax.add:
                pieces.append(jnp.mean(sl, axis=axis, keepdims=True))
            else:
                pieces.append(jnp.max(sl, axis=axis, keepdims=True))
        x = jnp.concatenate(pieces, axis=axis)
    return x


@defop()
def adaptive_avg_pool1d(x, output_size, data_format="NCL"):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _adaptive_pool(x, output_size, 1, fmt, jax.lax.add)


@defop()
def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, data_format, jax.lax.add)


@defop()
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, data_format, jax.lax.add)


@defop()
def adaptive_max_pool1d(x, output_size, return_mask=False, data_format="NCL"):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _adaptive_pool(x, output_size, 1, fmt, jax.lax.max)


@defop()
def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, data_format, jax.lax.max)


@defop()
def adaptive_max_pool3d(x, output_size, return_mask=False,
                        data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, data_format, jax.lax.max)


# -- with-index / fractional / unpool family (reference ops
#    max_pool2d_with_index, max_pool3d_with_index, fractional_max_pool2d/3d,
#    unpool, unpool3d — `phi/kernels/funcs/pooling.h`) ----------------------
def _window_positions(in_size, starts, ends):
    """Static (numpy) gather positions for variable windows: returns
    pos [out, kmax] clipped and valid [out, kmax] masks."""
    starts = np.asarray(starts, np.int64)
    ends = np.asarray(ends, np.int64)
    kmax = int((ends - starts).max())
    a = np.arange(kmax)[None, :]
    pos = starts[:, None] + a
    valid = (pos < ends[:, None]) & (pos >= 0) & (pos < in_size)
    return np.clip(pos, 0, in_size - 1), valid


def _windowed_max(x, dims, with_index):
    """Max (and argmax flat index) over per-output-cell windows.

    ``x`` is [N, C, *spatial]; ``dims`` is a list of (pos, valid) pairs
    from :func:`_window_positions`, one per spatial dim. One
    outer-product gather builds [N, C, O1, k1, O2, k2, ...]; a masked
    max (+ take-along argmax) reduces the k axes. The flat index is in
    the reference's convention: row-major over the unpadded spatial
    volume."""
    nd = len(dims)
    idx_arrays, valid, absidx = [], None, None
    spatial = x.shape[2:]
    for d, (pos, v) in enumerate(dims):
        shape = [1] * (2 * nd)
        shape[2 * d], shape[2 * d + 1] = pos.shape
        idx_arrays.append(jnp.asarray(pos.reshape(shape)))
        v = v.reshape(shape)
        valid = v if valid is None else (valid & v)
        p = pos.reshape(shape)
        # row-major flat index over the unpadded volume
        absidx = p if absidx is None else absidx * spatial[d] + p
    win = x[(Ellipsis, *idx_arrays)]          # [N, C, O1, k1, O2, k2, ...]
    inter = win.shape[2:]
    # interleaved -> grouped: [N, C, O1..On, k1..kn]
    perm_sp = [2 * d for d in range(nd)] + [2 * d + 1 for d in range(nd)]
    win = jnp.transpose(win, [0, 1] + [2 + p for p in perm_sp])
    vmask = jnp.asarray(
        np.transpose(np.broadcast_to(valid, inter), perm_sp))
    neg = jnp.asarray(-np.inf if jnp.issubdtype(x.dtype, jnp.floating)
                      else np.iinfo(np.dtype(x.dtype).name).min, x.dtype)
    win = jnp.where(vmask, win, neg)
    flat = win.reshape(win.shape[:2 + nd] + (-1,))   # [N,C,O...,K]
    out = jnp.max(flat, axis=-1)
    if not with_index:
        return out, None
    absflat = np.transpose(np.broadcast_to(absidx, inter), perm_sp)
    absflat = jnp.asarray(absflat.reshape(absflat.shape[:nd] + (-1,)))
    arg = jnp.argmax(flat, axis=-1)
    idx = jnp.take_along_axis(jnp.broadcast_to(absflat, flat.shape),
                              arg[..., None], axis=-1)[..., 0]
    return out, idx.astype(jnp.int32)


def _fixed_window_dims(spatial, kernel, stride, padding, ceil_mode):
    dims = []
    for s, k, st, p in zip(spatial, kernel, stride, padding):
        n_out = (s + 2 * p - k + (st - 1 if ceil_mode else 0)) // st + 1
        starts = np.arange(n_out) * st - p
        dims.append(_window_positions(s, starts, starts + k))
    return dims


@defop()
def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False):
    """Reference op `max_pool2d_with_index`: max pool returning the
    flat (h*W + w) argmax per window."""
    k = _tuple(kernel_size, 2)
    st = _tuple(stride, 2) if stride is not None else k
    p = _tuple(padding, 2)
    if global_pooling:
        k, st, p = x.shape[2:], x.shape[2:], (0, 0)
    dims = _fixed_window_dims(x.shape[2:], k, st, p, ceil_mode)
    return _windowed_max(x, dims, True)


@defop()
def max_pool3d_with_index(x, kernel_size, stride=None, padding=0,
                          global_pooling=False, adaptive=False,
                          ceil_mode=False):
    """Reference op `max_pool3d_with_index` (flat d*H*W + h*W + w)."""
    k = _tuple(kernel_size, 3)
    st = _tuple(stride, 3) if stride is not None else k
    p = _tuple(padding, 3)
    if global_pooling:
        k, st, p = x.shape[2:], x.shape[2:], (0, 0, 0)
    dims = _fixed_window_dims(x.shape[2:], k, st, p, ceil_mode)
    return _windowed_max(x, dims, True)


def _default_random_u():
    """Draw the fractional-pool offset from ``framework.random`` so
    ``paddle.seed()`` controls it like every other random op. The value
    is consumed by host-side window construction, so it is concretized
    here (tracing without an explicit ``random_u`` is an error, as it
    would bake one draw into the compiled program)."""
    from ...framework import random as framework_random

    key = framework_random.next_key()
    return float(jax.random.uniform(key, (), minval=0.1, maxval=0.9))


def _fractional_dims(spatial, out_sizes, kernel, u):
    """Reference fractional windows (`phi/kernels/funcs/pooling.h`
    FractionalStartIndex/EndIndex + FractionalRationalU)."""
    dims = []
    for d, (s, o) in enumerate(zip(spatial, out_sizes)):
        alpha = s / o
        ks = 0 if kernel is None else kernel[d]
        if ks > 0:
            uu = u
        else:
            base = s // o
            u_max1 = (base + 2) / alpha - 1
            u_max2 = (s + 1 - base) / alpha - (o - 1)
            uu = u * min(u_max1, u_max2)
        i = np.arange(o)
        starts = ((i + uu) * alpha).astype(np.int64) - int(uu * alpha)
        if ks > 0:
            ends = starts + ks
        else:
            ends = ((i + 1 + uu) * alpha).astype(np.int64) - int(uu * alpha)
        dims.append(_window_positions(s, starts, np.minimum(ends, s)))
    return dims


@defop()
def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False):
    """Fractional max pooling (Graham 2015; reference op
    `fractional_max_pool2d`). ``random_u`` fixes the pseudo-random
    offset; otherwise one is drawn per call."""
    o = _tuple(output_size, 2)
    k = _tuple(kernel_size, 2) if kernel_size is not None else None
    u = float(random_u) if random_u is not None else _default_random_u()
    dims = _fractional_dims(x.shape[2:], o, k, u)
    out, idx = _windowed_max(x, dims, return_mask)
    return (out, idx) if return_mask else out


@defop()
def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False):
    """3-D fractional max pooling (reference op
    `fractional_max_pool3d`)."""
    o = _tuple(output_size, 3)
    k = _tuple(kernel_size, 3) if kernel_size is not None else None
    u = float(random_u) if random_u is not None else _default_random_u()
    dims = _fractional_dims(x.shape[2:], o, k, u)
    out, idx = _windowed_max(x, dims, return_mask)
    return (out, idx) if return_mask else out


def _unpool(x, indices, out_spatial):
    """Scatter pooled values back at their argmax positions."""
    n, c = x.shape[:2]
    flat_len = int(np.prod(out_spatial))
    xf = x.reshape(n, c, -1)
    idxf = indices.reshape(n, c, -1).astype(jnp.int32)
    out = jnp.zeros((n, c, flat_len), x.dtype)
    out = out.at[jnp.arange(n)[:, None, None],
                 jnp.arange(c)[None, :, None], idxf].set(xf)
    return out.reshape((n, c) + tuple(out_spatial))


def _unpool_out_size(in_spatial, kernel, stride, padding, output_size):
    if output_size is not None:
        out = [int(s) for s in output_size]
        return out[-len(in_spatial):] if len(out) > len(in_spatial) else out
    return [(s - 1) * st - 2 * p + k
            for s, k, st, p in zip(in_spatial, kernel, stride, padding)]


@defop(name="unpool")
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW"):
    """Inverse of max_pool2d(return_mask=True) (reference op `unpool`,
    `phi/kernels/gpu/unpool_kernel.cu`)."""
    k = _tuple(kernel_size, 2)
    st = _tuple(stride, 2) if stride is not None else k
    p = _tuple(padding, 2)
    return _unpool(x, indices,
                   _unpool_out_size(x.shape[2:], k, st, p, output_size))


@defop(name="unpool3d")
def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW"):
    """Inverse of max_pool3d_with_index (reference op `unpool3d`)."""
    k = _tuple(kernel_size, 3)
    st = _tuple(stride, 3) if stride is not None else k
    p = _tuple(padding, 3)
    return _unpool(x, indices,
                   _unpool_out_size(x.shape[2:], k, st, p, output_size))


@defop()
def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL"):
    """Inverse of max_pool1d(return_mask=True) (reference
    `nn/functional/pooling.py:max_unpool1d`)."""
    k = _tuple(kernel_size, 1)
    st = _tuple(stride, 1) if stride is not None else k
    p = _tuple(padding, 1)
    return _unpool(x, indices,
                   _unpool_out_size(x.shape[2:], k, st, p, output_size))


@defop()
def pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
           exclusive=True, data_format="NCHW", pooling_type="max",
           global_pooling=False, adaptive=False):
    """Legacy unified pooling op (reference legacy op `pool2d`)."""
    if global_pooling:
        kernel_size = x.shape[2:] if data_format == "NCHW" else x.shape[1:3]
        stride, padding = kernel_size, 0
    if adaptive:
        fn = (adaptive_max_pool2d if pooling_type == "max"
              else adaptive_avg_pool2d)
        out = fn(x, kernel_size, data_format=data_format)
        return getattr(out, "_data", out)
    if pooling_type == "max":
        return _max_pool(x, kernel_size, stride, padding, 2, data_format,
                         ceil_mode)
    return _avg_pool(x, kernel_size, stride, padding, 2, data_format,
                     exclusive, ceil_mode)


@defop()
def pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
           exclusive=True, data_format="NCDHW", pooling_type="max",
           global_pooling=False, adaptive=False):
    """Legacy unified pooling op (reference legacy op `pool3d`)."""
    if global_pooling:
        kernel_size = x.shape[2:] if data_format == "NCDHW" \
            else x.shape[1:4]
        stride, padding = kernel_size, 0
    if adaptive:
        fn = (adaptive_max_pool3d if pooling_type == "max"
              else adaptive_avg_pool3d)
        out = fn(x, kernel_size, data_format=data_format)
        return getattr(out, "_data", out)
    if pooling_type == "max":
        return _max_pool(x, kernel_size, stride, padding, 3, data_format,
                         ceil_mode)
    return _avg_pool(x, kernel_size, stride, padding, 3, data_format,
                     exclusive, ceil_mode)
