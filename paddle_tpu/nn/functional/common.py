"""Common NN functionals: linear, dropout, embedding, normalize, ...

Reference: `python/paddle/nn/functional/common.py`, `input.py`.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.registry import defop
from ...framework.tensor import Tensor, run_op
from ...framework import random as frandom

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "embedding", "normalize", "cosine_similarity", "bilinear",
    "label_smooth", "interpolate", "upsample", "pixel_shuffle",
    "pixel_unshuffle", "channel_shuffle", "unfold", "fold", "one_hot",
    "grid_sample", "affine_grid", "linear_interp", "bilinear_interp",
    "nearest_interp", "bicubic_interp", "trilinear_interp",
    "class_center_sample", "pad3d", "fused_softmax_mask",
    "fused_softmax_mask_upper_triangle"]


@defop()
def linear(x, weight, bias=None):
    """y = x @ W (+ b). W is [in_features, out_features] — the reference's
    Linear convention (`python/paddle/nn/layer/common.py` Linear)."""
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """Reference: nn/functional/common.py dropout. RNG comes from the
    framework generator (named-state aware for model parallelism)."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x.scale(1 - p) if hasattr(x, "scale") else x * (1 - p)
        return x
    if p == 1.0:
        return x * 0 if isinstance(x, Tensor) else Tensor(jnp.zeros_like(x))
    key = frandom.next_key()

    def fn(x_, key_):
        shape = list(x_.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key_, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, x_ / (1.0 - p), 0).astype(x_.dtype)
        return jnp.where(keep, x_, 0).astype(x_.dtype)

    return run_op("dropout", fn, (x, key))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    """SELU-preserving dropout (reference common.py alpha_dropout)."""
    if not training or p == 0.0:
        return x
    key = frandom.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(x_, key_):
        keep = jax.random.bernoulli(key_, 1.0 - p, x_.shape)
        a = ((1.0 - p) * (1.0 + p * alpha_p ** 2)) ** -0.5
        b = -a * alpha_p * p
        return (a * jnp.where(keep, x_, alpha_p) + b).astype(x_.dtype)

    return run_op("alpha_dropout", fn, (x, key))


@defop()
def embedding(x, weight, padding_idx=None, sparse=False):
    """Lookup rows of ``weight`` by integer ids ``x``.

    Reference: nn/functional/input.py embedding — with ``padding_idx`` the
    output row is zero and no gradient flows to that row.
    """
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x != padding_idx)[..., None]
        out = jnp.where(mask, out, 0).astype(out.dtype)
    return out


@defop()
def normalize(x, p=2, axis=1, epsilon=1e-12):
    norm = jnp.linalg.norm(x, ord=p, axis=int(axis), keepdims=True)
    return x / jnp.maximum(norm, epsilon)


@defop()
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=int(axis))
    n1 = jnp.linalg.norm(x1, axis=int(axis))
    n2 = jnp.linalg.norm(x2, axis=int(axis))
    return dot / jnp.maximum(n1 * n2, eps)


@defop()
def bilinear(x1, x2, weight, bias=None):
    """out[n,o] = x1[n,i] W[o,i,j] x2[n,j] (+ b). Reference common.py
    bilinear."""
    y = jnp.einsum("ni,oij,nj->no", x1, weight, x2)
    if bias is not None:
        y = y + bias
    return y


@defop()
def label_smooth(label, prior_dist=None, epsilon=0.1):
    c = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / c


def one_hot(x, num_classes, name=None):
    from ...tensor import creation  # reuse registered op if present
    def fn(x_):
        return jax.nn.one_hot(x_, num_classes, dtype=jnp.float32)
    return run_op("one_hot", fn, (x,), differentiable=False)


@defop()
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = int(upscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c // (r * r), r, r, h, w)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, r, r, c // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(n, h * r, w * r, c // (r * r))


@defop()
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = int(downscale_factor)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // r, r, w // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(n, c * r * r, h // r, w // r)
    n, h, w, c = x.shape
    x = x.reshape(n, h // r, r, w // r, r, c)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return x.reshape(n, h // r, w // r, c * r * r)


@defop()
def channel_shuffle(x, groups, data_format="NCHW"):
    g = int(groups)
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, g, c // g, h, w)
        x = jnp.transpose(x, (0, 2, 1, 3, 4))
        return x.reshape(n, c, h, w)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, g, c // g)
    x = jnp.transpose(x, (0, 1, 2, 4, 3))
    return x.reshape(n, h, w, c)


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(e) for e in v)
    return (int(v),) * n


@defop()
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (reference common.py unfold): NCHW -> [N, C*kh*kw, L]."""
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    if isinstance(paddings, (list, tuple)) and len(paddings) == 4:
        p = tuple(int(e) for e in paddings)  # (top, bottom, left, right)
    else:
        ph, pw = _pair(paddings, 2)
        p = (ph, ph, pw, pw)
    n, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])))
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    # patches: [N, C*kh*kw, out_h, out_w]
    return patches.reshape(n, c * kh * kw, -1)


@defop()
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """col2im, the adjoint of unfold (reference common.py fold)."""
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    dh, dw = _pair(dilations)
    p = _pair(paddings, 2)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    out_h = (oh + 2 * p[0] - dh * (kh - 1) - 1) // sh + 1
    out_w = (ow + 2 * p[1] - dw * (kw - 1) - 1) // sw + 1
    cols = x.reshape(n, c, kh, kw, out_h, out_w)
    padded = jnp.zeros((n, c, oh + 2 * p[0], ow + 2 * p[1]), dtype=x.dtype)
    for i in range(kh):
        for j in range(kw):
            hi = i * dh
            wj = j * dw
            padded = padded.at[:, :, hi:hi + sh * out_h:sh,
                               wj:wj + sw * out_w:sw].add(cols[:, :, i, j])
    return padded[:, :, p[0]:p[0] + oh, p[1]:p[1] + ow]


def _interp_coords(out_size, in_size, align_corners, align_mode):
    """Source coordinate of each output index for the linear/cubic
    families (reference `phi/kernels/funcs/interpolate_function.h`:
    align_corners -> i*(in-1)/(out-1); else align_mode 0 -> half-pixel,
    align_mode 1 -> i*scale)."""
    i = jnp.arange(out_size, dtype=jnp.float32)
    if align_corners:
        return i * (in_size - 1) / max(out_size - 1, 1)
    if align_mode == 1:
        return i * in_size / out_size
    return (i + 0.5) * in_size / out_size - 0.5


def _axis_weights(w, axis, ndim, out_size):
    shape = [1] * ndim
    shape[axis] = out_size
    return w.reshape(shape)


def _interp_axis_linear(x, axis, coords):
    """Separable 2-tap lerp along ``axis`` at float ``coords``."""
    n = x.shape[axis]
    c = jnp.clip(coords, 0, n - 1)
    i0 = jnp.clip(jnp.floor(c).astype(jnp.int32), 0, n - 1)
    i1 = jnp.clip(i0 + 1, 0, n - 1)
    w = (c - i0).astype(x.dtype)
    w = _axis_weights(w, axis, x.ndim, coords.shape[0])
    return jnp.take(x, i0, axis) * (1 - w) + jnp.take(x, i1, axis) * w


def _cubic_kernel(t, a=-0.75):
    """Keys cubic convolution weights for the 4 taps at offsets
    (-1, 0, 1, 2) given fractional position t (reference
    `phi/kernels/funcs/interpolate_function.h:cubic_interp`)."""
    def w1(d):   # |d| <= 1
        return (a + 2) * d ** 3 - (a + 3) * d ** 2 + 1

    def w2(d):   # 1 < |d| < 2
        return a * d ** 3 - 5 * a * d ** 2 + 8 * a * d - 4 * a

    return [w2(t + 1), w1(t), w1(1 - t), w2(2 - t)]


def _interp_axis_cubic(x, axis, coords):
    n = x.shape[axis]
    f = jnp.floor(coords)
    t = (coords - f).astype(jnp.float32)
    base = f.astype(jnp.int32)
    out = 0
    for k, wk in enumerate(_cubic_kernel(t)):
        idx = jnp.clip(base + (k - 1), 0, n - 1)
        w = _axis_weights(wk.astype(x.dtype), axis, x.ndim, coords.shape[0])
        out = out + jnp.take(x, idx, axis) * w
    return out


def _interp_axis_nearest(x, axis, out_size, align_corners):
    n = x.shape[axis]
    i = jnp.arange(out_size, dtype=jnp.float32)
    if align_corners:
        idx = jnp.round(i * (n - 1) / max(out_size - 1, 1))
    else:
        idx = jnp.floor(i * n / out_size)
    return jnp.take(x, jnp.clip(idx.astype(jnp.int32), 0, n - 1), axis)


@defop()
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW"):
    """Resize (reference `nn/functional/common.py:interpolate`; CUDA
    kernels `phi/kernels/gpu/interpolate_kernel.cu`). TPU-native:
    separable per-axis gather + lerp/cubic taps that XLA fuses — all
    five modes honor align_corners / align_mode exactly; `area`
    delegates to adaptive average pooling."""
    channel_last = not data_format.startswith("NC")
    spatial_axes = list(range(1, x.ndim - 1)) if channel_last \
        else list(range(2, x.ndim))
    spatial = [x.shape[a] for a in spatial_axes]
    if size is None:
        if scale_factor is None:
            raise ValueError("one of size/scale_factor is required")
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor] * len(spatial)
        size = [int(s * float(f)) for s, f in zip(spatial, sf)]
    else:
        size = [int(s) for s in
                (size if isinstance(size, (list, tuple)) else [size])]
    if len(size) != len(spatial):
        raise ValueError(
            f"size has {len(size)} dims but input has {len(spatial)} "
            "spatial dims")
    if mode == "area":
        from .pooling import (adaptive_avg_pool1d, adaptive_avg_pool2d,
                              adaptive_avg_pool3d)
        pool = {1: adaptive_avg_pool1d, 2: adaptive_avg_pool2d,
                3: adaptive_avg_pool3d}[len(size)]
        if channel_last:
            x = jnp.moveaxis(x, -1, 1)
        out = pool(x, size)
        out = getattr(out, "_data", out)
        return jnp.moveaxis(out, 1, -1) if channel_last else out
    if mode == "nearest":
        for a, s in zip(spatial_axes, size):
            x = _interp_axis_nearest(x, a, s, align_corners)
        return x
    if mode in ("linear", "bilinear", "trilinear"):
        fn = _interp_axis_linear
    elif mode == "bicubic":
        fn = _interp_axis_cubic
    else:
        raise ValueError(f"unsupported mode {mode!r}")
    for a, s in zip(spatial_axes, size):
        coords = _interp_coords(s, x.shape[a], align_corners,
                                0 if mode == "bicubic" else align_mode)
        x = fn(x, a, coords)
    return x


def _interp_family(op_name, mode, ndim):
    @defop(name=op_name)
    def op(x, size=None, scale_factor=None, align_corners=False,
           align_mode=0, data_format="NCHW"):
        if x.ndim != ndim:
            raise ValueError(f"{op_name} expects {ndim}-D input")
        # reuse the raw-jax interpolate body (x is already an array here)
        return interpolate.__wrapped__(
            x, size=size, scale_factor=scale_factor, mode=mode,
            align_corners=align_corners, align_mode=align_mode,
            data_format=data_format)
    op.__name__ = op_name
    op.__doc__ = (f"Reference op `{op_name}` "
                  "(`paddle/phi/api/yaml/legacy_ops.yaml`): the "
                  f"{mode} resize kernel behind F.interpolate.")
    return op


linear_interp = _interp_family("linear_interp", "linear", 3)
bilinear_interp = _interp_family("bilinear_interp", "bilinear", 4)
nearest_interp = _interp_family("nearest_interp", "nearest", 4)
bicubic_interp = _interp_family("bicubic_interp", "bicubic", 4)
trilinear_interp = _interp_family("trilinear_interp", "trilinear", 5)


@defop()
def affine_grid(theta, out_shape, align_corners=True):
    """Sampling grid for a batch of affine transforms (reference op
    `affine_grid`, `phi/kernels/impl/affine_grid_kernel_impl.h`).
    theta [N,2,3] -> grid [N,H,W,2]; theta [N,3,4] -> [N,D,H,W,3]."""
    out_shape = [int(s) for s in out_shape]
    spatial = out_shape[2:]

    def axis_coords(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        # half-pixel centers: (2i + 1)/n - 1
        return (2 * jnp.arange(n, dtype=jnp.float32) + 1) / n - 1

    coords = [axis_coords(n) for n in spatial]
    mesh = jnp.meshgrid(*coords, indexing="ij")     # D,H,W order
    # grid coordinate order is (x, y[, z]) = reversed spatial
    base = jnp.stack(list(reversed(mesh)) + [jnp.ones_like(mesh[0])],
                     axis=-1)                       # [*spatial, ndim+1]
    base = base.astype(theta.dtype)
    return jnp.einsum("...i,nji->n...j", base, theta)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, data_format="NCHW", name=None):
    return interpolate(x, size=size, scale_factor=scale_factor, mode=mode,
                       align_corners=align_corners, data_format=data_format)


@defop(differentiable=True)
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """Sample ``x [N, C, H, W]`` at normalized ``grid [N, Ho, Wo, 2]``
    coordinates in [-1, 1] (reference `nn/functional/vision.py:grid_sample`,
    CUDA kernel `phi/kernels/gpu/grid_sample_kernel.cu`). TPU-native:
    the bilinear taps are four gathers + a weighted sum XLA fuses."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"mode must be bilinear/nearest, got {mode!r}")
    if padding_mode not in ("zeros", "border"):
        raise ValueError(
            f"padding_mode must be zeros/border, got {padding_mode!r}")
    n, c, h, w = x.shape
    gx = grid[..., 0]
    gy = grid[..., 1]
    if align_corners:
        fx = (gx + 1) * (w - 1) / 2
        fy = (gy + 1) * (h - 1) / 2
    else:
        fx = ((gx + 1) * w - 1) / 2
        fy = ((gy + 1) * h - 1) / 2

    def gather(yi, xi):
        yi_c = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xi_c = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        flat = (yi_c * w + xi_c).reshape(n, 1, -1)       # [N, 1, Ho*Wo]
        xf = x.reshape(n, c, h * w)
        out = jnp.take_along_axis(
            xf, jnp.broadcast_to(flat, (n, c, flat.shape[-1])), axis=-1)
        return out.reshape(n, c, *gx.shape[1:])

    def in_bounds(yi, xi):
        if padding_mode == "border":
            return jnp.ones_like(yi, dtype=x.dtype)
        return ((yi >= 0) & (yi <= h - 1) & (xi >= 0)
                & (xi <= w - 1)).astype(x.dtype)

    if mode == "nearest":
        yi = jnp.round(fy)
        xi = jnp.round(fx)
        return gather(yi, xi) * in_bounds(yi, xi)[:, None]

    y0 = jnp.floor(fy)
    x0 = jnp.floor(fx)
    wy1 = fy - y0
    wx1 = fx - x0
    out = 0.0
    for (yy, xx, wgt) in [
            (y0, x0, (1 - wy1) * (1 - wx1)),
            (y0, x0 + 1, (1 - wy1) * wx1),
            (y0 + 1, x0, wy1 * (1 - wx1)),
            (y0 + 1, x0 + 1, wy1 * wx1)]:
        out = out + gather(yy, xx) * (wgt * in_bounds(yy, xx))[:, None]
    return out


def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """PartialFC class-center sampling (reference op
    `class_center_sample`, `phi/kernels/gpu/class_center_sample_kernel.cu`
    — `nn/functional/common.py:2104`): keep every positive class, fill
    up to ``num_samples`` with random negatives, remap labels into the
    sampled index space. Sampling is host-side bookkeeping (the result
    feeds a partial FC layer); returns (remapped_label,
    sampled_class_center)."""
    import jax as _jax
    import numpy as _np

    from ...framework import random as _framework_random
    from ...framework.tensor import Tensor as _T

    lbl = _np.asarray(getattr(label, "_data", label)).reshape(-1)
    pos = _np.unique(lbl)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = _np.setdiff1d(_np.arange(num_classes), pos,
                                 assume_unique=True)
        # negatives drawn through framework.random: paddle.seed()
        # controls the sample like every other random op
        perm = _np.asarray(_jax.random.permutation(
            _framework_random.next_key(), len(neg_pool)))
        extra = neg_pool[perm[:num_samples - len(pos)]]
        sampled = _np.sort(_np.concatenate([pos, extra]))
    remap = _np.full((num_classes,), -1, _np.int64)
    remap[sampled] = _np.arange(len(sampled))
    return (_T(jnp.asarray(remap[lbl])),
            _T(jnp.asarray(sampled.astype(_np.int64))))


@defop()
def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    """5-D padding (reference op `pad3d`,
    `phi/kernels/gpu/pad3d_kernel.cu`). ``paddings`` is
    (left, right, top, bottom, front, back) on the spatial dims."""
    pl, pr, pt, pb, pf, pbk = (int(p) for p in paddings)
    if data_format == "NCDHW":
        cfg = ((0, 0), (0, 0), (pf, pbk), (pt, pb), (pl, pr))
    else:
        cfg = ((0, 0), (pf, pbk), (pt, pb), (pl, pr), (0, 0))
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, cfg, mode="constant", constant_values=value)
    return jnp.pad(x, cfg, mode=jmode)


@defop()
def fused_softmax_mask(x, mask):
    """softmax(x + mask) in one op (reference fused op
    `fused_softmax_mask`, `phi/kernels/fusion/gpu/`) — XLA fuses the
    add into the softmax; the op exists for API parity."""
    return jax.nn.softmax(x.astype(jnp.float32) + mask.astype(jnp.float32),
                          axis=-1).astype(x.dtype)


@defop()
def fused_softmax_mask_upper_triangle(x):
    """Causal-masked softmax (reference
    `fused_softmax_mask_upper_triangle`): positions above the diagonal
    are -inf before the softmax."""
    s = x.shape[-1]
    mask = jnp.triu(jnp.full((s, s), -1e9, jnp.float32), k=1)
    return jax.nn.softmax(x.astype(jnp.float32) + mask, axis=-1) \
        .astype(x.dtype)
