"""``paddle.static`` — the static-graph surface.

Reference: `python/paddle/static/` (Program builders, ``InputSpec``,
save/load_inference_model). TPU-native: there is no separate static
graph — ``jit.to_static`` traces imperative code into one XLA program —
so this namespace keeps the pieces that still mean something:
``InputSpec`` (shape/dtype specs with symbolic batch dims for export)
and the inference-model save/load entry points, which delegate to
``paddle_tpu.jit.save``/``load`` (StableHLO serialization).
"""

from __future__ import annotations

import numpy as np

from ..framework.dtype import convert_dtype

__all__ = ["InputSpec", "save_inference_model", "load_inference_model"]


class InputSpec:
    """Shape/dtype/name spec (reference `static/input.py` InputSpec).
    ``None`` dims are symbolic (any size at run time — exported models
    stay shape-polymorphic in them, each ``None`` independent). Use a
    STRING dim (e.g. ``InputSpec(["batch", 8])``) to share one symbol
    across inputs whose sizes must match."""

    def __init__(self, shape, dtype="float32", name=None,
                 stop_gradient=False):
        self.shape = list(shape)
        self.dtype = np.dtype(convert_dtype(dtype))
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(list(tensor.shape), str(tensor.dtype), name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype.name}, "
                f"name={self.name})")


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """Reference `static/io.py:save_inference_model`, mapped to the
    traced-program world: there is no Program object, so ``fetch_vars``
    is the model itself (an ``nn.Layer`` or callable) and ``feed_vars``
    its input specs (InputSpec / example Tensors). Delegates to
    ``jit.save`` — StableHLO + params — which ``load_inference_model``
    (and the inference ``Predictor``) loads back."""
    from ..jit import save as jit_save
    from ..nn import Layer

    model = fetch_vars
    if isinstance(model, (list, tuple)):
        if len(model) != 1:
            raise ValueError(
                "save_inference_model expects ONE model (nn.Layer or "
                "callable) as fetch_vars — traced programs replace the "
                "reference's fetch-variable lists")
        model = model[0]
    if not (isinstance(model, Layer) or callable(model)):
        raise TypeError(
            "fetch_vars must be the nn.Layer (or callable) to export; "
            f"got {type(model).__name__}")
    specs = feed_vars
    if specs is not None and not isinstance(specs, (list, tuple)):
        specs = [specs]
    return jit_save(model, path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit import load as jit_load
    return jit_load(path_prefix)
