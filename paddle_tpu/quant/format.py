"""The quantized weight format and model-level quantize APIs.

Format (one weight ``w [K, N]``, contraction axis K):

- ``q      [K, N]  int8`` — the quantized values, same layout as ``w``;
- ``scales [ceil(K/B), N]  f32`` — per-(row-block, column) absmax
  scales: ``scales[kb, n] = max(|w[kb*B:(kb+1)*B, n]|) / 127``, so
  ``w[k, n] ~= q[k, n] * scales[k // B, n]``.

B (the block size) is the knob: ``PADDLE_TPU_WEIGHT_BLOCK`` fleet-wide,
or per call. The layout is deliberately *tile-streamable*: a VMEM tile
of ``B`` weight rows carries exactly one contiguous scale row
``scales[kb, :]`` (N minor in both arrays), so the later megakernel
stage can stream ``(int8 rows, their scales)`` pairs without a gather —
the same sidecar-rides-the-same-index pattern the int8 KV pages use.

Stacked MoE expert weights ``[E, K, N]`` quantize per expert to
``[E, K, N]`` int8 + ``[E, ceil(K/B), N]`` scales.

``quantize_model`` swaps every ``nn.Linear`` under the model for a
:class:`~paddle_tpu.quant.layers.WeightOnlyLinear` and asks layers that
expose ``quantize_weights(block)`` (the stacked-expert MoE FFN) to
self-quantize. ``lm_head`` is skipped by default: the output projection
is the most quality-sensitive matmul, its weight is shared with the
fused-CE training path, and at ~vocab x hidden it is a small fraction
of decode bytes on real configs — the standard weight-only recipe.
Embeddings are lookups, not matmuls, and stay float too.
"""

from __future__ import annotations

import os

import jax.numpy as jnp

from ..framework.tensor import Tensor

#: default per-block rows covered by one scale row — 128 matches the
#: MXU/lane tile so a kernel weight tile never straddles a scale row
DEFAULT_BLOCK = 128


def _raw(a):
    return a._data if isinstance(a, Tensor) else jnp.asarray(a)


def default_block():
    """Fleet default block size (``PADDLE_TPU_WEIGHT_BLOCK`` wins)."""
    env = os.environ.get("PADDLE_TPU_WEIGHT_BLOCK", "")
    return int(env) if env else DEFAULT_BLOCK


def effective_block(k, block=None):
    """The block size actually used for a contraction dim of ``k``:
    the requested (or default) block, clamped to ``k`` — a weight
    shorter than one block gets exactly one scale row, and the clamped
    value keeps ``K % B == 0`` for kernel-friendly shapes like
    ``K < DEFAULT_BLOCK`` tiny configs."""
    b = int(block) if block else default_block()
    if b <= 0:
        raise ValueError(f"weight block must be positive, got {b}")
    return min(b, int(k))


def quantize_weight(w, block=None):
    """``[*, K, N]`` float -> ``([*, K, N] int8, [*, ceil(K/B), N] f32)``.

    Symmetric per-block absmax: each scale is ``absmax / 127`` so the
    full block range maps onto ``[-127, 127]`` (-128 unused, keeping
    the grid symmetric). An all-zero block gets scale 0 and dequantizes
    to exact zeros."""
    arr = _raw(w).astype(jnp.float32)
    if arr.ndim < 2:
        raise ValueError(f"weight must be at least 2-D, got {arr.shape}")
    k, n = arr.shape[-2], arr.shape[-1]
    b = effective_block(k, block)
    kb = -(-k // b)
    pad = kb * b - k
    if pad:
        cfg = [(0, 0)] * (arr.ndim - 2) + [(0, pad), (0, 0)]
        arr = jnp.pad(arr, cfg)
    blocked = arr.reshape(arr.shape[:-2] + (kb, b, n))
    scales = (jnp.max(jnp.abs(blocked), axis=-2) / 127.0) \
        .astype(jnp.float32)
    q = jnp.clip(
        jnp.round(blocked / jnp.maximum(scales, 1e-12)[..., None, :]),
        -127, 127).astype(jnp.int8)
    q = q.reshape(arr.shape[:-2] + (kb * b, n))[..., :k, :]
    return q, scales


def dequantize_weight(q, scales, block=None):
    """Exact inverse map of the format: ``q * scales`` broadcast over
    row blocks, f32 out. ``block`` must be the value quantization used
    (the default resolves the same knob ``quantize_weight`` did)."""
    qa, sa = _raw(q), _raw(scales)
    k = qa.shape[-2]
    b = effective_block(k, block)
    if sa.shape[-2] != -(-k // b):
        raise ValueError(
            f"scales rows {sa.shape[-2]} do not match ceil({k}/{b}); "
            "pass the block size the weight was quantized with")
    s = jnp.repeat(sa.astype(jnp.float32), b, axis=-2)[..., :k, :]
    return qa.astype(jnp.float32) * s


def quantize_model(model, block=None, skip=("lm_head",)):
    """Swap every quantizable layer under ``model`` (in place) for its
    weight-only int8 serving form. Returns the model; raises if nothing
    was quantizable (a config error, not a silent no-op).

    - ``nn.Linear`` -> :class:`WeightOnlyLinear` (int8 + scale buffers,
      dequant-on-use forward);
    - layers exposing ``quantize_weights(block)`` (the stacked-expert
      ``LlamaMoEMLP``) self-quantize in place;
    - attribute names in ``skip`` (default: ``lm_head``) stay float.
    """
    from .. import nn
    from .layers import WeightOnlyLinear

    count = 0

    def walk(layer):
        nonlocal count
        for name, sub in list(layer._sub_layers.items()):
            if name in skip:
                continue
            if isinstance(sub, WeightOnlyLinear):
                count += 1
            elif isinstance(sub, nn.Linear):
                layer._sub_layers[name] = \
                    WeightOnlyLinear.from_linear(sub, block=block)
                count += 1
            elif hasattr(sub, "quantize_weights"):
                if not getattr(sub, "weight_block", None):
                    sub.quantize_weights(block)
                count += 1
            else:
                walk(sub)

    walk(model)
    if count == 0:
        raise ValueError(
            "quantize_model found no quantizable layers (nn.Linear or "
            "quantize_weights-capable) under the model")
    return model


def is_quantized(model):
    """True when any layer under ``model`` is in the weight-only form."""
    from .layers import WeightOnlyLinear

    for _, sub in model.named_sublayers(include_self=True):
        if isinstance(sub, WeightOnlyLinear):
            return True
        if getattr(sub, "weight_block", None):
            return True
    return False


def model_weight_block(model):
    """The block size of a quantized model (first quantized layer
    found), or None when the model is float."""
    from .layers import WeightOnlyLinear

    for _, sub in model.named_sublayers(include_self=True):
        if isinstance(sub, WeightOnlyLinear):
            return sub.weight_block
        b = getattr(sub, "weight_block", None)
        if b:
            return int(b)
    return None


def serving_weight_bytes(model):
    """``(actual_bytes, bf16_baseline_bytes, weight_elems)`` over the
    model's state (params + persistable buffers).

    ``actual_bytes`` counts everything as stored — int8 weights, f32
    scale sidecars, float leftovers (embeddings, norms, lm_head).
    ``bf16_baseline_bytes`` is what the same *weights* would cost at
    bf16 (2 bytes/elem, sidecars excluded — they don't exist in the
    float model). The ratio is the serving capacity win; per-param
    bytes (``actual / elems``) feeds the
    ``serving_weight_bytes_per_param`` gauge."""
    actual = baseline = elems = 0
    for name, t in model.state_dict().items():
        arr = _raw(t)
        nbytes = int(arr.size) * jnp.dtype(arr.dtype).itemsize
        actual += nbytes
        if name.rsplit(".", 1)[-1].endswith("_scale"):
            continue        # sidecar: real bytes, not a weight elem
        elems += int(arr.size)
        baseline += 2 * int(arr.size)
    return actual, baseline, elems
