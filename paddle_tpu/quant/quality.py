"""Quality gate: quantized model vs float model on bundled prompts.

Weight-only quantization is only a win if the served tokens don't
change — this module is the measurement, and its bars are what
``bench_weight_int8`` (and the CI test) enforce:

- ``greedy_match`` — teacher-forced position-wise argmax agreement
  between the two models over every prompt position. Teacher-forced
  (both models read the SAME prefix at every position) so the number
  measures per-step decision flips, not compounding divergence; bar
  :data:`GREEDY_MATCH_BAR`.
- ``max_err`` / ``mean_err`` — absolute logits error, judged relative
  to the float model's logit magnitude (the same 0.05x-scale
  convention every ``*_parity_ok`` kernel gate in bench.py uses);
  bars :data:`LOGITS_MAX_ERR_REL` / :data:`LOGITS_MEAN_ERR_REL`.

The prompt set is real ASCII text (byte-token convention of the
serving frontend's ``ByteTokenizer``: token id = byte value, so every
prompt encodes under any vocab >= 128) bundled here so the gate needs
no downloads and every environment measures the same thing.
"""

from __future__ import annotations

import numpy as np

from ..observability import metrics as _om

__all__ = ["GREEDY_MATCH_BAR", "LOGITS_MAX_ERR_REL",
           "LOGITS_MEAN_ERR_REL", "bundled_prompts",
           "bundled_prompt_ids", "fit_on_prompts", "logits_quality"]

#: fraction of teacher-forced positions whose argmax must agree
GREEDY_MATCH_BAR = 0.99

#: max abs logits error budget, as a fraction of max |float logit|
LOGITS_MAX_ERR_REL = 0.05

#: mean abs logits error budget, as a fraction of max |float logit|
LOGITS_MEAN_ERR_REL = 0.01

#: real-text ASCII prompts (byte-tokenizable under any vocab >= 128)
_PROMPTS = (
    "The quick brown fox jumps over the lazy dog.",
    "In the beginning the framework compiled one program per shape.",
    "Weight-only quantization halves the bytes a decode step moves.",
    "A page table maps each sequence to its cached key-value pages.",
    "def attention(q, k, v):\n    return softmax(q @ k.T) @ v\n",
    "To be, or not to be, that is the question.",
)


def bundled_prompts():
    """The raw bundled prompt strings."""
    return list(_PROMPTS)


def bundled_prompt_ids(vocab_size=None):
    """Byte-encode the bundled prompts (frontend ``ByteTokenizer``
    convention: id = byte value). ``vocab_size`` (when given) wraps ids
    into range for sub-byte vocabularies."""
    out = []
    for p in _PROMPTS:
        ids = list(p.encode("utf-8"))
        if vocab_size:
            ids = [i % int(vocab_size) for i in ids]
        out.append(ids)
    return out


def fit_on_prompts(model, steps=40, lr=1e-2):
    """Briefly fit ``model`` on next-token prediction of the bundled
    prompts (Adam, a few seconds for test-sized configs).

    The gate needs a model with *predictive signal*: a random-init
    model's logits are near-iid, so its argmax margins are ties and
    greedy-match measures tie-breaking noise instead of quantization
    damage. A few fitting steps give decisive margins (the regime real
    checkpoints live in), making the greedy bar measure what it
    claims. Returns the final loss."""
    import paddle_tpu as paddle

    ids = bundled_prompt_ids(model.config.vocab_size)
    width = max(len(i) for i in ids)
    x = np.zeros((len(ids), width), np.int32)
    y = np.full((len(ids), width), -100, np.int32)
    for row, seq in enumerate(ids):
        x[row, :len(seq)] = seq
        y[row, :len(seq) - 1] = seq[1:]
    xt, yt = paddle.to_tensor(x), paddle.to_tensor(y)
    opt = paddle.optimizer.Adam(learning_rate=lr,
                                parameters=model.parameters())
    loss = None
    for _ in range(int(steps)):
        loss, _ = model(xt, labels=yt)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(loss.numpy()) if loss is not None else float("nan")


def logits_quality(model_fp, model_q, prompts=None):
    """Teacher-forced comparison of two ``LlamaForCausalLM``-shaped
    models (``model(ids) -> logits``) over the bundled prompts.

    Returns a report dict — ``max_err``, ``mean_err``, ``ref_scale``
    (max |float logit|), ``greedy_match``, ``positions``, and
    ``passes`` (all bars hold) — and publishes the three
    quality-gate gauges."""
    import paddle_tpu as paddle

    vocab = getattr(getattr(model_fp, "config", None), "vocab_size",
                    None)
    if prompts is None:
        prompts = bundled_prompt_ids(vocab)
    max_err = 0.0
    err_sum = 0.0
    ref_scale = 0.0
    count = 0
    match = 0
    total = 0
    for ids in prompts:
        x = paddle.to_tensor(np.asarray([ids], np.int32))
        lf = model_fp(x).astype("float32").numpy()[0]     # [T, V]
        lq = model_q(x).astype("float32").numpy()[0]
        d = np.abs(lf - lq)
        max_err = max(max_err, float(d.max()))
        err_sum += float(d.sum())
        count += d.size
        ref_scale = max(ref_scale, float(np.abs(lf).max()))
        match += int((lf.argmax(-1) == lq.argmax(-1)).sum())
        total += lf.shape[0]
    mean_err = err_sum / max(count, 1)
    greedy = match / max(total, 1)
    scale = max(ref_scale, 1.0)
    report = {
        "max_err": max_err,
        "mean_err": mean_err,
        "ref_scale": ref_scale,
        "greedy_match": greedy,
        "positions": total,
        "passes": bool(greedy >= GREEDY_MATCH_BAR
                       and max_err <= LOGITS_MAX_ERR_REL * scale
                       and mean_err <= LOGITS_MEAN_ERR_REL * scale),
    }
    _om.gauge(
        "quant_greedy_match_rate",
        "teacher-forced argmax agreement of the weight-quantized "
        "model vs float on the bundled prompts (bar 0.99)"
    ).set(greedy)
    _om.gauge(
        "quant_logits_max_err",
        "max abs logits error of the weight-quantized model vs float "
        "on the bundled prompts").set(max_err)
    _om.gauge(
        "quant_logits_mean_err",
        "mean abs logits error of the weight-quantized model vs float "
        "on the bundled prompts").set(mean_err)
    return report
