"""In-VMEM dequant matmul: ``y = x @ (int8 w * per-block scales)``.

The decode-side projection kernel for weight-only int8 serving
(:mod:`paddle_tpu.quant.format`): HBM streams int8 weight tiles plus
their f32 scale rows; the dequantize (upcast x scale) happens in VMEM
right before one whole-K f32-accumulated ``dot_general``. Grid is
``(M/bm, N/bn)`` with whole-K tiles — each output tile is ONE dot over
the full contraction, so the accumulation order matches the XLA
reference's single dot and the two paths are bitwise-identical (the
``test_weight_quant`` parity bar, same contract as ``grouped_gemm``).

``supported()`` gates the kernel the same way ``grouped_gemm`` does:
TPU backend only (the interpreter is orders slower than XLA — CPU
always takes the reference), lane/sublane-friendly shapes, a scale
layout that tiles exactly (``K % B == 0``), and one grid step's blocks
within the VMEM budget. Everything else transparently serves
:func:`dequant_matmul_xla` — the *exact-parity* formulation (the same
elementwise dequant products, the same single f32 dot), not an
approximation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..framework.tensor import Tensor, run_op
from .format import effective_block

__all__ = ["dequant_matmul", "dequant_matmul_xla", "supported"]

#: VMEM budget for one grid step's blocks (x tile + int8 w tile + scale
#: tile + dequantized f32 w + out tile), kept well under the ~16 MB/core
#: ceiling (see pallas_guide.md)
_VMEM_BUDGET = 12 * 1024 * 1024


def _interpret():
    return jax.default_backend() != "tpu"


def _raw(a):
    return a._data if isinstance(a, Tensor) else a


def _dequant_w(q, scales, block):
    """The dequant expression — shared between the kernel body and the
    XLA formulation so both compute the SAME elementwise products
    (bitwise parity needs identical operands, and an elementwise
    multiply of identical operands is deterministic)."""
    k, n = q.shape[-2], q.shape[-1]
    kb = scales.shape[-2]
    w = q.astype(jnp.float32)
    if kb * block == k:
        shape = q.shape[:-2] + (kb, block, n)
        return (w.reshape(shape)
                * scales[..., :, None, :]).reshape(q.shape)
    # ragged last block (K % B != 0): broadcast scales by repeat+crop —
    # same per-element products, just not kernel-tileable
    s = jnp.repeat(scales, block, axis=-2)[..., :k, :]
    return w * s


def _vmem_bytes(bm, k, kb, bn, x_itemsize):
    return (bm * k * x_itemsize     # x tile
            + k * bn                # int8 weight tile
            + kb * bn * 4           # f32 scale tile
            + k * bn * 4            # dequantized f32 weight
            + bm * bn * 4)          # f32 accumulator / out tile


def _blocks(m, k, kb, n, itemsize):
    """(block_m, block_n): row tiles sublane-aligned and capped at 128;
    n tiles lane-sized when N allows, shrunk under the VMEM budget."""
    bm = min(128, -(-m // 8) * 8)
    if n % 256 == 0:
        bn = 256
    elif n % 128 == 0:
        bn = 128
    else:
        bn = n          # one lane tile; N % 8 == 0 by supported()
    while bn > 128 and _vmem_bytes(bm, k, kb, bn, itemsize) \
            > _VMEM_BUDGET:
        bn //= 2
    return bm, bn


def supported(x, w_q, scales, block=None):
    """Pallas-path preconditions for ``x [M, K] @ dequant(w_q [K, N])``:
    TPU backend, int8 weights, scales ``[K/B, N]`` tiling K exactly,
    K/N sublane/lane friendly, one grid step within the VMEM budget.
    Anything else takes the exact XLA formulation."""
    xa, qa, sa = _raw(x), _raw(w_q), _raw(scales)
    if _interpret():
        return False
    if getattr(xa, "ndim", 0) != 2 or getattr(qa, "ndim", 0) != 2 \
            or getattr(sa, "ndim", 0) != 2:
        return False
    m, k = xa.shape
    kw, n = qa.shape
    if kw != k or sa.shape[1] != n:
        return False
    if jnp.dtype(qa.dtype) != jnp.int8 \
            or jnp.dtype(sa.dtype) != jnp.float32:
        return False
    b = effective_block(k, block)
    if k % b or sa.shape[0] != k // b:
        return False    # whole-K reshape tiling only (exact parity)
    if m == 0 or k % 8 or n % 8:
        return False
    itemsize = jnp.dtype(xa.dtype).itemsize
    bm, bn = _blocks(m, k, k // b, n, itemsize)
    if n % bn:
        return False
    return _vmem_bytes(bm, k, k // b, bn, itemsize) <= _VMEM_BUDGET


def _dq_kernel(x_ref, w_ref, s_ref, o_ref, *, block):
    w = _dequant_w(w_ref[...], s_ref[...], block)
    o_ref[...] = jax.lax.dot_general(
        x_ref[...].astype(jnp.float32), w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


@functools.lru_cache(maxsize=64)
def _make_dq(m, k, kb, n, block, bm, bn, out_dtype, interpret):
    grid = (m // bm, n // bn)
    return pl.pallas_call(
        functools.partial(_dq_kernel, block=block),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda mi, ni: (mi, 0)),
            pl.BlockSpec((k, bn), lambda mi, ni: (0, ni)),
            pl.BlockSpec((kb, bn), lambda mi, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda mi, ni: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=interpret,
    )


def _kernel_impl(x, q, scales, block):
    """Pallas dispatch (raw arrays, 2-D x). Rows pad to the tile
    explicitly (each out row depends only on its own x row, so pad rows
    can't contaminate real ones) and crop after."""
    m, k = x.shape
    n = q.shape[1]
    kb = scales.shape[0]
    bm, bn = _blocks(m, k, kb, n, jnp.dtype(x.dtype).itemsize)
    mp = -(-m // bm) * bm
    xp = jnp.pad(x, ((0, mp - m), (0, 0))) if mp != m else x
    call = _make_dq(mp, k, kb, n, block, bm, bn, x.dtype, _interpret())
    y = call(xp, q, scales)
    return y[:m] if mp != m else y


def _xla_impl(x, q, scales, block):
    """The exact-parity XLA formulation: identical dequant products,
    one whole-K f32 dot — the fallback AND the parity bar."""
    w = _dequant_w(q, scales, block)
    y = jax.lax.dot_general(
        x.astype(jnp.float32), w,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return y.astype(x.dtype)


def _dequant_matmul(x, q, scales, block=None, use_kernel=None):
    """Raw-array entry: x ``[..., K]``, auto-selecting the kernel when
    :func:`supported` holds (``use_kernel`` forces a path — the parity
    tests run the kernel in interpret mode through ``True``)."""
    k = x.shape[-1]
    b = effective_block(k, block)
    lead = x.shape[:-1]
    x2 = x.reshape((-1, k))
    if use_kernel is None:
        use_kernel = supported(x2, q, scales, b)
    impl = _kernel_impl if use_kernel else _xla_impl
    y = impl(x2, q, scales, b)
    return y.reshape(lead + (q.shape[-1],))


def dequant_matmul(x, w_q, scales, block=None):
    """Tensor-level ``x @ dequant(w_q)``: int8 weights + per-block
    scales stay int8 in HBM, dequantized in VMEM on use. Serving-side
    only (not differentiable — quantized weights are frozen)."""
    return run_op(
        "dequant_matmul",
        lambda a, q, s: _dequant_matmul(a, q, s, block),
        (x, w_q, scales), differentiable=False)


def dequant_matmul_xla(x, w_q, scales, block=None):
    """The exact-parity XLA formulation (parity bar / forced fallback)."""
    return run_op(
        "dequant_matmul_xla",
        lambda a, q, s: _dequant_matmul(a, q, s, block,
                                        use_kernel=False),
        (x, w_q, scales), differentiable=False)
