"""Bridge from the QAT/PTQ module's deployed form to serving weights.

``paddle_tpu.quantization``'s ``convert`` emits ``ConvertedLinear``:
int8 weights + ONE per-tensor absmax scale, dequanting as
``w = q * (scale / 127)``. The serving format
(:mod:`paddle_tpu.quant.format`) is the per-block generalization of
exactly that math — so a QAT'd model deploys **without
requantization**: the int8 values are reused verbatim and the
per-tensor scale is replicated into the per-block sidecar
(``scales[kb, n] = scale / 127`` for every block/column). The bridged
layer's dequantized weight is bitwise-identical to the source's — the
round-trip test pins it.

PTQ models that calibrated an activation scale carry semantics the
weight-only serving path drops (input snapping to the int8 grid);
``strict=True`` (the default) refuses those, ``strict=False`` bridges
weight-only and discards the activation scale.
"""

from __future__ import annotations

import numpy as np

from ..quantization import ConvertedLinear
from .format import effective_block
from .layers import WeightOnlyLinear

__all__ = ["bridge_linear", "bridge_model"]


def bridge_linear(converted, block=None, strict=True):
    """One ``ConvertedLinear`` -> :class:`WeightOnlyLinear`, lossless
    (same int8 values, replicated scale sidecar — no requantization)."""
    if not isinstance(converted, ConvertedLinear):
        raise TypeError(
            f"expected quantization.ConvertedLinear, got "
            f"{type(converted).__name__}")
    if converted.act_scale is not None:
        if strict:
            raise ValueError(
                "ConvertedLinear carries a calibrated act_scale; the "
                "weight-only serving path drops activation snapping — "
                "pass strict=False to bridge weight-only anyway")
    q = converted.weight_int8.numpy()
    k, n = q.shape
    b = effective_block(k, block)
    kb = -(-k // b)
    # ConvertedLinear dequants w = q * (scale / 127): replicating that
    # value into every [kb, n] slot reproduces the identical products
    per_block = float(np.asarray(converted.weight_scale.numpy(),
                                 np.float32)) / 127.0
    scales = np.full((kb, n), per_block, np.float32)
    return WeightOnlyLinear(q, scales, bias=converted.bias, block=b)


def bridge_model(model, block=None, strict=True):
    """Swap every ``ConvertedLinear`` under ``model`` (in place) for
    its bridged serving form; returns the number swapped."""
    count = 0

    def walk(layer):
        nonlocal count
        for name, sub in list(layer._sub_layers.items()):
            if isinstance(sub, ConvertedLinear):
                layer._sub_layers[name] = \
                    bridge_linear(sub, block=block, strict=strict)
                count += 1
            else:
                walk(sub)

    walk(model)
    return count
