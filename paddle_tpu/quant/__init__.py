"""``paddle_tpu.quant`` — serving-side weight-only int8 quantization.

Decode is weight-bandwidth-bound: every projection the mixed serving
program touches streams its full weight matrix from HBM per step.
Storing those weights as int8 with per-block absmax f32 scale sidecars
(:mod:`.format`) halves the bytes a decode step moves — the same
multiplicative lever the int8 KV pages proved for the cache side — and
the dequant happens on-use, in VMEM next to the matmul
(:mod:`.kernels`), so HBM only ever sees int8.

Pieces:

- :mod:`.format` — the quantized weight format (`[K, N]` int8 +
  ``[ceil(K/B), N]`` f32 scales, block size a knob) and the
  ``quantize_model`` / ``dequantize_weight`` APIs;
- :mod:`.kernels` — the Pallas dequant-matmul (int8 x scale in VMEM,
  f32 accumulate) with its exact-parity XLA formulation and a
  ``supported()`` gate in the ``grouped_gemm`` style;
- :mod:`.layers` — ``WeightOnlyLinear``, the drop-in serving form of
  ``nn.Linear`` (int8 + scale buffers, dequant-on-use forward);
- :mod:`.bridge` — lossless converter from the QAT/PTQ module's
  ``convert`` output into this serving format (no requantization);
- :mod:`.checkpoint` — ``save_quantized`` / ``load_quantized`` on the
  ``CheckpointManager`` atomic-commit/CRC contract;
- :mod:`.quality` — the bundled-prompt quality gate (max/mean logits
  error + greedy-match rate of the quantized model vs the float one).

The engine knob is ``LlamaServingEngine(weight_dtype="int8")`` /
``PADDLE_TPU_WEIGHT_DTYPE=int8``; ``bf16`` (the default) leaves the
model untouched — the old path byte for byte.
"""

from .format import (DEFAULT_BLOCK, default_block, dequantize_weight,
                     effective_block, is_quantized, model_weight_block,
                     quantize_model, quantize_weight,
                     serving_weight_bytes)
from .kernels import dequant_matmul, dequant_matmul_xla, supported
from .layers import WeightOnlyLinear
from .bridge import bridge_linear, bridge_model
from .checkpoint import load_quantized, save_quantized
from . import quality

__all__ = [
    "DEFAULT_BLOCK", "default_block", "effective_block",
    "quantize_weight", "dequantize_weight", "quantize_model",
    "is_quantized", "model_weight_block", "serving_weight_bytes",
    "dequant_matmul", "dequant_matmul_xla", "supported",
    "WeightOnlyLinear", "bridge_linear", "bridge_model",
    "save_quantized", "load_quantized", "quality",
]
