"""``WeightOnlyLinear`` — the serving form of ``nn.Linear``.

Weight lives as int8 + per-block f32 scales (persistable *buffers*, so
they ride ``state_dict`` / checkpointing and trace into compiled
serving programs through ``StaticFunction``'s state collection), and
the forward dequantizes on use through :func:`dequant_matmul` — Pallas
in VMEM on TPU, the exact XLA formulation elsewhere. Bias (when the
source layer had one) stays a float Parameter.
"""

from __future__ import annotations

import numpy as np

from .. import nn
from ..framework.tensor import Tensor
from .format import effective_block, quantize_weight
from .kernels import dequant_matmul

__all__ = ["WeightOnlyLinear"]


class WeightOnlyLinear(nn.Layer):
    """Drop-in dequant-on-use linear: ``y = x @ (q * scales) (+ b)``.

    Construct from pre-quantized data (the checkpoint / QAT-bridge
    path) or via :meth:`from_linear` (quantize a float layer). The
    block size is part of the layer (it shapes the scale sidecar and
    the kernel's tiling), not re-derived per call.
    """

    def __init__(self, weight_int8, weight_scale, bias=None, block=None):
        super().__init__()
        q = weight_int8.numpy() if isinstance(weight_int8, Tensor) \
            else np.asarray(weight_int8)
        s = weight_scale.numpy() if isinstance(weight_scale, Tensor) \
            else np.asarray(weight_scale)
        if q.ndim != 2 or s.ndim != 2:
            raise ValueError(
                f"expected 2-D weight + scales, got {q.shape} / "
                f"{s.shape}")
        self.in_features, self.out_features = int(q.shape[0]), \
            int(q.shape[1])
        self.weight_block = effective_block(self.in_features, block)
        kb = -(-self.in_features // self.weight_block)
        if s.shape != (kb, self.out_features):
            raise ValueError(
                f"scales {s.shape} do not match ceil({self.in_features}"
                f"/{self.weight_block}) x {self.out_features}")
        self.register_buffer("weight_int8",
                             Tensor(np.ascontiguousarray(q, np.int8)))
        self.register_buffer("weight_scale",
                             Tensor(np.ascontiguousarray(s, np.float32)))
        self.bias = bias

    @classmethod
    def from_linear(cls, linear, block=None):
        """Quantize a float ``nn.Linear`` into the serving form (the
        float weight is dropped; bias is carried over as-is)."""
        b = effective_block(linear.weight.shape[-2], block)
        q, s = quantize_weight(linear.weight, b)
        return cls(np.asarray(q), np.asarray(s), bias=linear.bias,
                   block=b)

    def forward(self, x):
        y = dequant_matmul(x, self.weight_int8, self.weight_scale,
                           self.weight_block)
        if self.bias is not None:
            y = y + self.bias
        return y

    def to(self, device=None, dtype=None, blocking=None):
        # model-wide dtype casts (``model.bfloat16()``) must not touch
        # the format's invariants: int8 weights are non-floating
        # (Layer.to skips them) and the f32 scale sidecars are pinned
        # here — bf16 scales would change the dequant products and
        # fail the kernel's supported() gate
        out = super().to(device=device, dtype=dtype, blocking=blocking)
        import jax.numpy as jnp
        s = self._buffers["weight_scale"]
        if s._data.dtype != jnp.float32:
            s._data = s._data.astype(jnp.float32)
        return out

    def extra_repr(self):
        return (f"in={self.in_features}, out={self.out_features}, "
                f"block={self.weight_block}, "
                f"bias={self.bias is not None}")
