"""Quantized checkpoints on the ``CheckpointManager`` contract.

``save_quantized`` writes the int8 weights + f32 scale sidecars through
the SAME two-phase commit ``CheckpointManager`` gives training state: a
``step_N.tmp`` staging dir, fsync, a ``COMMITTED`` marker carrying
per-file sizes + CRC-32, then an atomic rename — so a torn quantized
checkpoint is impossible and ``verify_step`` audits it like any other.
npz stores int8 natively (1 byte/elem) and the sidecars as f32, which
is where the ~2x restart-bytes win over a bf16 checkpoint comes from.

``load_quantized`` restores into a model: if the model is still float
it is first structurally quantized (``quantize_model``) so every
target tensor exists with the right dtype/shape, then
``restore_latest`` verifies CRCs and loads — the loaded values
*replace* the throwaway quantization, giving warm-restart parity with
the saved engine. The block size must match the one the checkpoint was
saved with (sidecar shapes are part of the format).
"""

from __future__ import annotations

import glob
import json
import os

from ..distributed.checkpoint_manager import CheckpointManager
from .format import is_quantized, model_weight_block, quantize_model

__all__ = ["save_quantized", "load_quantized"]


def save_quantized(model, root, step=0, block=None, max_to_keep=5):
    """Quantize ``model`` in place (if not already) and commit its
    state under ``root``; returns the committed step directory."""
    if not is_quantized(model):
        quantize_model(model, block=block)
    mgr = CheckpointManager(root, max_to_keep=max_to_keep,
                            async_save=False)
    # the block size rides along as a checkpoint object so a cold
    # restore doesn't need it out-of-band (sidecar shapes alone don't
    # determine it: ceil(K/b) is not injective in b)
    state = dict(model.state_dict())
    state["quant_meta"] = {"block": int(model_weight_block(model))}
    mgr.save(state, step, blocking=True)
    return mgr.step_dir(step)


def _saved_block(mgr):
    """Peek the newest committed step's metadata for the block size
    ``save_quantized`` recorded; None for pre-format or absent roots."""
    steps = mgr.committed_steps()
    if not steps:
        return None
    d = mgr.step_dir(steps[-1])
    for mf in sorted(glob.glob(os.path.join(d, "metadata_p*.json"))):
        try:
            with open(mf) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            continue
        b = meta.get("objects", {}).get("quant_meta.block")
        if b is not None:
            return int(b)
    return None


def load_quantized(model, root, block=None):
    """Restore the latest quantized checkpoint under ``root`` into
    ``model`` (structurally quantizing it first when needed); returns
    the restored step, or None when no committed checkpoint exists.

    The block size is read from the checkpoint itself when not given —
    ``block=`` only matters for pre-``quant_meta`` checkpoints."""
    mgr = CheckpointManager(root, async_save=False)
    if not is_quantized(model):
        if block is None:
            block = _saved_block(mgr)
        quantize_model(model, block=block)
    # restore over the model's own keys; the quant_meta object in the
    # checkpoint is peek-only and deliberately absent from the target
    return mgr.restore_latest(model.state_dict())
