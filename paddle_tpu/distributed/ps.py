"""Parameter-server seam: sparse tables with pull/push over TCPStore.

Reference capability: the brpc parameter server
(`paddle/fluid/distributed/ps/` — `brpc_ps_server.cc`, sparse tables
`ps/table/memory_sparse_table.cc`, Python `ps/the_one_ps.py`). SURVEY §7
descopes full PS mode ("design seam for sparse tables later"); this
module is that seam made concrete: a working PS with the reference's
core semantics — server-resident sparse embedding tables with lazy row
init, workers pulling rows by id and pushing gradients, server-side
SGD/Adagrad — over the native C++ TCPStore (`paddle_tpu/native`) as the
rendezvous + transport, so it runs multi-process today and the table/
optimizer layer is transport-agnostic for a future brpc-class backend.

The dense path never goes through the PS (GSPMD collectives own it);
only the sparse-recommendation path does, like the reference's
heterogeneous PS mode.
"""

from __future__ import annotations

import io
import threading

import numpy as np

__all__ = ["SparseTable", "DiskSparseTable", "PSServer", "PSClient"]


def _dumps(arr):
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return buf.getvalue()


def _loads(data):
    return np.load(io.BytesIO(data), allow_pickle=False)


class SparseTable:
    """Server-side sparse embedding table (reference
    `memory_sparse_table.cc`): rows materialize on first touch via the
    initializer; push applies the configured rule server-side."""

    def __init__(self, dim, initializer=None, optimizer="sgd", lr=0.1,
                 seed=0):
        self.dim = dim
        self.lr = lr
        self.optimizer = optimizer
        if optimizer not in ("sgd", "adagrad"):
            raise ValueError(f"unsupported table optimizer {optimizer!r}")
        self._rows: dict[int, np.ndarray] = {}
        self._accum: dict[int, np.ndarray] = {}
        self._rng = np.random.RandomState(seed)
        self._init = initializer or (
            lambda rng, dim: (rng.rand(dim).astype(np.float32) - 0.5) * 0.2)
        self._lock = threading.Lock()

    def _row(self, rid):
        r = self._rows.get(rid)
        if r is None:
            r = self._init(self._rng, self.dim)
            self._rows[rid] = r
        return r

    def pull(self, ids):
        with self._lock:
            return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads):
        """Apply gradients; duplicate ids accumulate (the reference's
        merge-by-key before update)."""
        grads = np.asarray(grads, np.float32)
        with self._lock:
            merged: dict[int, np.ndarray] = {}
            for i, g in zip(ids, grads):
                i = int(i)
                merged[i] = merged.get(i, 0) + g
            for i, g in merged.items():
                row = self._row(i)
                if self.optimizer == "sgd":
                    row -= self.lr * g
                else:  # adagrad
                    acc = self._accum.setdefault(
                        i, np.zeros(self.dim, np.float32))
                    acc += g * g
                    row -= self.lr * g / (np.sqrt(acc) + 1e-10)

    def num_rows(self):
        with self._lock:
            return len(self._rows)

    def state_dict(self):
        with self._lock:
            return {"rows": dict(self._rows), "accum": dict(self._accum)}


class PSServer:
    """Serves tables over a TCPStore: request keys
    ``ps/req/<seq>`` hold ``(op, table, payload)``; replies land in
    ``ps/rsp/<seq>``. One dispatcher thread; table ops are locked, so
    concurrent workers are safe. (Transport is a KV rendezvous store, not
    brpc — adequate for the sparse path's pull/push batching.)"""

    def __init__(self, tables, store=None, port=0):
        from ..native import TCPStore
        self.tables = dict(tables)
        self.store = store or TCPStore(port=port, is_master=True)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self.store.port

    def _serve(self):
        seq = 0
        misses = 0
        while not self._stop.is_set():
            key = f"ps/req/{seq}"
            try:
                payload = self.store.get(key, timeout=0.25)
            except TimeoutError:
                # a claimed-but-never-written seq (crashed worker) must
                # not wedge the in-order dispatcher: skip after ~10 s,
                # unless no request was ever claimed this far
                claimed = self.store.add("ps/seq", 0)
                if seq < claimed:
                    misses += 1
                    if misses > 40:
                        misses = 0
                        seq += 1
                continue
            misses = 0
            self.store.delete_key(key)
            try:
                head, body = payload.split(b"\n", 1)
                op, tname = head.decode().split(":")
                table = self.tables[tname]
                if op == "pull":
                    ids = _loads(body)
                    self.store.set(f"ps/rsp/{seq}", _dumps(table.pull(ids)))
                elif op == "push":
                    blob = _loads(body)
                    ids, grads = blob[:, 0].astype(np.int64), blob[:, 1:]
                    table.push(ids, grads)
                    self.store.set(f"ps/rsp/{seq}", b"ok")
                elif op == "nrows":
                    self.store.set(f"ps/rsp/{seq}",
                                   str(table.num_rows()).encode())
                else:
                    self.store.set(f"ps/rsp/{seq}",
                                   b"err:unknown op " + op.encode())
            except Exception as e:  # report instead of wedging the loop
                self.store.set(f"ps/rsp/{seq}", b"err:" + repr(e).encode())
            seq += 1

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=5)
        self.store.close()


class PSClient:
    """Worker-side handle. Requests are globally ordered via the store's
    atomic ``add`` on the sequence counter, so any number of workers can
    interleave pulls and pushes."""

    def __init__(self, host="127.0.0.1", port=0, store=None, timeout=30.0):
        from ..native import TCPStore
        self.store = store or TCPStore(host=host, port=port,
                                       timeout=timeout)
        self.timeout = timeout

    def _request(self, op, table, body):
        seq = self.store.add("ps/seq", 1) - 1
        self.store.set(f"ps/req/{seq}", f"{op}:{table}".encode()
                       + b"\n" + body)
        rsp = self.store.get(f"ps/rsp/{seq}", timeout=self.timeout)
        self.store.delete_key(f"ps/rsp/{seq}")
        if rsp.startswith(b"err:"):
            raise RuntimeError(f"PS server error: {rsp[4:].decode()}")
        return rsp

    def pull(self, table, ids):
        """Fetch rows for ``ids`` -> float32 [len(ids), dim]."""
        return _loads(self._request(
            "pull", table, _dumps(np.asarray(ids, np.int64))))

    def push(self, table, ids, grads):
        """Send gradients for ``ids``; server applies its update rule."""
        ids = np.asarray(ids, np.float32).reshape(-1, 1)
        grads = np.asarray(grads, np.float32)
        self._request("push", table, _dumps(
            np.concatenate([ids, grads], axis=1)))

    def num_rows(self, table):
        return int(self._request("nrows", table, b""))

    def close(self):
        self.store.close()


class DiskSparseTable(SparseTable):
    """Disk-backed sparse table (reference `ps/table/
    ssd_sparse_table.cc` — rocksdb-resident rows with a hot in-memory
    cache): rows live in a sqlite file, an LRU cache of ``cache_rows``
    keeps the hot working set in memory, evictions write through. The
    pull/push/optimizer semantics are :class:`SparseTable`'s — servers
    can swap table classes without touching the protocol."""

    def __init__(self, dim, path, initializer=None, optimizer="sgd",
                 lr=0.1, seed=0, cache_rows=100_000):
        super().__init__(dim, initializer, optimizer, lr, seed)
        import sqlite3

        self._cache_rows = int(cache_rows)
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS rows "
            "(id INTEGER PRIMARY KEY, val BLOB, accum BLOB)")
        self._db.commit()

    def _evict_if_needed(self):
        # self._rows doubles as the LRU cache (dict preserves insertion
        # order; re-inserted-on-touch keys move to the back)
        evicted = False
        while len(self._rows) > self._cache_rows:
            rid, val = next(iter(self._rows.items()))
            self._flush_row(rid)
            del self._rows[rid]
            self._accum.pop(rid, None)
            evicted = True
        if evicted:
            # one commit for the whole eviction batch: without it the
            # write-through sits in sqlite's open transaction and a
            # crash loses every evicted row
            self._db.commit()

    def _flush_row(self, rid):
        acc = self._accum.get(rid)
        self._db.execute(
            "INSERT OR REPLACE INTO rows (id, val, accum) VALUES (?,?,?)",
            (int(rid), self._rows[rid].tobytes(),
             None if acc is None else acc.tobytes()))

    def _row(self, rid):
        r = self._rows.get(rid)
        if r is not None:
            # LRU touch
            del self._rows[rid]
            self._rows[rid] = r
            return r
        cur = self._db.execute(
            "SELECT val, accum FROM rows WHERE id = ?", (int(rid),))
        hit = cur.fetchone()
        if hit is not None:
            r = np.frombuffer(hit[0], np.float32).copy()
            if hit[1] is not None:
                self._accum[rid] = np.frombuffer(hit[1],
                                                 np.float32).copy()
        else:
            r = self._init(self._rng, self.dim)
        self._rows[rid] = r
        self._evict_if_needed()
        return r

    def flush(self):
        """Write every cached row through to disk (checkpoint barrier)."""
        with self._lock:
            for rid in list(self._rows):
                self._flush_row(rid)
            self._db.commit()

    def num_rows(self):
        with self._lock:
            cached = set(self._rows)
            on_disk = {r[0] for r in self._db.execute(
                "SELECT id FROM rows")}
            return len(cached | on_disk)

    def state_dict(self):
        self.flush()
        with self._lock:
            rows, accum = {}, {}
            for rid, val, acc in self._db.execute(
                    "SELECT id, val, accum FROM rows"):
                rows[rid] = np.frombuffer(val, np.float32).copy()
                if acc is not None:
                    accum[rid] = np.frombuffer(acc, np.float32).copy()
            rows.update({int(k): v for k, v in self._rows.items()})
            return {"rows": rows, "accum": accum}

    def close(self):
        self.flush()
        self._db.close()
