"""``paddle_tpu.distributed`` — mesh-sharded (GSPMD) parallelism.

Reference surface: `python/paddle/distributed/__init__.py` (shard_tensor /
reshard / collective API / fleet hybrid parallel). TPU-native design: a
``ProcessMesh`` wraps ``jax.sharding.Mesh``; placements map to
``PartitionSpec``; collectives are XLA collectives over ICI/DCN; pipeline
p2p is collective-permute.
"""

from .process_mesh import ProcessMesh, get_mesh, set_mesh, init_mesh  # noqa: F401
from .placement import Placement, Shard, Replicate, Partial  # noqa: F401
from .api import (  # noqa: F401
    shard_tensor, dtensor_from_fn, reshard, shard_layer, shard_optimizer,
    unshard_dtensor, to_partition_spec,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, all_reduce, all_gather,
    all_gather_object, reduce_scatter, alltoall, broadcast, reduce,
    scatter, barrier, send, recv, isend, irecv, wait,
)
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv, is_initialized,
)
from .mp_layers import (  # noqa: F401
    ColumnSequenceParallelLinear, RowSequenceParallelLinear,
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from . import p2p  # noqa: F401
from . import pipeline  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import save_state_dict, load_state_dict  # noqa: F401
from . import checkpoint_manager  # noqa: F401
from .checkpoint_manager import CheckpointManager  # noqa: F401
from .ring_attention import ring_attention, ulysses_attention  # noqa: F401
from .recompute import recompute  # noqa: F401
from . import fleet  # noqa: F401
from .parallel import DataParallel, shard_dataloader, ShardDataloader  # noqa: F401
from . import auto_tuner  # noqa: F401
from .watchdog import (  # noqa: F401
    StepWatchdog, ElasticManager, FileStore, StaleEpochError,
)
from .pipeline import pipeline_spmd  # noqa: F401
from . import collective  # noqa: F401
from ..native import TCPStore  # noqa: F401  (C++ rendezvous store)
from . import ps  # noqa: F401  (sparse parameter-server seam)
from . import rpc  # noqa: F401  (control-plane RPC over TCPStore)

__all__ = [
    "TCPStore",
    "ProcessMesh", "get_mesh", "set_mesh", "init_mesh",
    "Placement", "Shard", "Replicate", "Partial",
    "shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
    "shard_optimizer", "unshard_dtensor", "to_partition_spec",
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce",
    "all_gather", "all_gather_object", "reduce_scatter", "alltoall",
    "broadcast", "reduce", "scatter", "barrier", "send", "recv",
    "isend", "irecv", "wait",
    "init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
    "is_initialized", "CheckpointManager",
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "ParallelCrossEntropy", "p2p",
]
