"""Activation recomputation (gradient checkpointing).

Reference: `python/paddle/distributed/fleet/recompute/recompute.py`
(re-runs the forward segment in backward instead of storing its
activations, with RNG-state replay). TPU-native mechanics: the segment's
pure function is wrapped in ``jax.checkpoint`` before the tape records it
— ``jax.vjp`` then saves only the segment INPUTS and re-derives the
intermediate activations during the backward sweep. RNG draws made while
tracing the segment are baked into the jaxpr, so the recomputed forward
replays the exact same randomness (the reference's
``preserve_rng_state=True`` behavior, by construction).
"""

from __future__ import annotations

import jax

from ..framework.tensor import Tensor, no_grad, run_op

__all__ = ["recompute"]


def recompute(function, *args, preserve_rng_state=True, use_reentrant=True,
              policy=None, **kwargs):
    """Run ``function(*args, **kwargs)`` with activation checkpointing.

    ``function`` may be an ``nn.Layer`` (its parameters keep gradient
    flow) or any Tensor-level callable. Tensor ``args`` are the
    checkpoint boundary: only they (plus parameters) are saved for
    backward.
    """
    from ..nn import Layer

    if isinstance(function, Layer):
        params = list(function.parameters())
    else:
        # a bound method of a Layer (e.g. ``layer.forward``) must thread
        # its owner's parameters too — otherwise they bake into the
        # checkpointed jaxpr as constants and silently stop training
        owner = getattr(function, "__self__", None)
        params = list(owner.parameters()) if isinstance(owner, Layer) \
            else []
    tensor_args = list(args)
    n_args = len(tensor_args)

    def pure(*arrays):
        arg_arrays = arrays[:n_args]
        param_arrays = arrays[n_args:]
        saved = [(p._data, p._node) for p in params]
        try:
            for p, a in zip(params, param_arrays):
                p._data = a
                p._node = None
            ins = [Tensor(a) if not isinstance(a, Tensor)
                   and hasattr(a, "dtype") else a for a in arg_arrays]
            # run the segment WITHOUT tape recording: recording would
            # make each inner op pre-split its jax.vjp, erasing
            # custom_vjp boundaries (the Pallas flash kernel's bwd rule)
            # from the graph the outer jax.checkpoint differentiates.
            # Grad flows through the checkpoint's own AD instead.
            with no_grad():
                out = function(*ins, **kwargs)
            if isinstance(out, (tuple, list)):
                return tuple(o._data if isinstance(o, Tensor) else o
                             for o in out)
            return out._data if isinstance(out, Tensor) else out
        finally:
            for p, (d, nd) in zip(params, saved):
                p._data, p._node = d, nd

    if policy == "dots":
        # save matmul outputs, recompute the cheap elementwise chain —
        # near-zero extra FLOPs, still sheds the big activation tails
        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    ckpt = jax.checkpoint(pure, policy=policy)
    return run_op("recompute", ckpt, tuple(tensor_args) + tuple(params))
