from . import main

main()
