"""``python -m paddle_tpu.distributed.launch`` — the process launcher.

Reference: `python/paddle/distributed/launch/main.py` +
`launch/controllers/collective.py:22` (``CollectiveController`` spawning
one process per device with ``PADDLE_*`` env, master rendezvous in
`controllers/master.py:73`).

TPU-native shape: ONE process per host (each process drives all its
local chips; intra-host needs no process group — GSPMD compiles the
collectives), so ``--nproc_per_node`` defaults to 1 and exists for
CPU-simulation runs. The launcher:

- assigns ranks ``node_rank * nproc + local``,
- exports the reference-shaped env (``PADDLE_TRAINER_ID``,
  ``PADDLE_TRAINERS_NUM``, ``PADDLE_MASTER``) that
  ``init_parallel_env`` turns into ``jax.distributed.initialize``,
- tees each worker's output to ``<log_dir>/workerlog.<rank>``,
- waits on all workers, kills the rest when any fails, and exits with
  the first failure code (the reference's watcher behavior,
  `launch/controllers/watcher.py`).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from ...observability import metrics as _om

__all__ = ["launch", "launch_elastic", "main"]

#: seconds a SIGTERM'd worker gets before SIGKILL — survivors parked in
#: a blocking collective shrug off SIGTERM
_TERM_GRACE = 10.0


def _terminate_survivors(procs, pending, grace=_TERM_GRACE):
    """SIGTERM every still-running worker in ``pending``, then SIGKILL
    whatever outlives the grace period (reference watcher behavior,
    escalated — a worker stuck in a hung collective must not stall the
    launcher forever)."""
    for j in pending:
        if procs[j].poll() is None:
            procs[j].send_signal(signal.SIGTERM)
    deadline = time.monotonic() + grace
    for j in pending:
        left = deadline - time.monotonic()
        try:
            procs[j].wait(timeout=max(0.1, left))
        except subprocess.TimeoutExpired:
            procs[j].kill()
            try:
                procs[j].wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass


def _launch_metrics():
    """Supervisor-side elastic counters (live in the launcher process)."""
    return {
        "restarts": _om.counter(
            "elastic_restarts_total",
            "elastic generations re-bootstrapped after a failure"),
        "failures": _om.counter(
            "elastic_worker_failures_total",
            "worker processes that exited nonzero"),
        "world": _om.gauge(
            "elastic_world_size", "workers in the current generation"),
    }


def launch(script_args, nnodes=1, node_rank=0, nproc_per_node=1,
           master=None, log_dir="log", env_extra=None):
    """Spawn workers for ``script_args`` (list: script + its argv)."""
    world = nnodes * nproc_per_node
    if nnodes > 1 and master is None:
        raise ValueError(
            "--master host:port is required for multi-node launches "
            "(a localhost default would leave non-zero nodes waiting on "
            "a coordinator that does not exist)")
    if world > 1 and master is None:
        master = "127.0.0.1:23456"
    os.makedirs(log_dir, exist_ok=True)
    procs, logs = [], []
    try:
        for local in range(nproc_per_node):
            rank = node_rank * nproc_per_node + local
            env = dict(os.environ)
            env.update(env_extra or {})
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_LOCAL_RANK": str(local),
                "PADDLE_NNODES": str(nnodes),
                "FLAGS_selected_devices": str(local),
            })
            if master:
                env["PADDLE_MASTER"] = master
            log = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable] + list(script_args),
                env=env, stdout=log, stderr=subprocess.STDOUT))
        # wait; on any failure tear down the rest (reference watcher
        # behavior, escalated SIGTERM -> grace -> SIGKILL)
        exit_code = 0
        pending = set(range(len(procs)))
        while pending:
            for i in sorted(pending):
                ret = procs[i].poll()
                if ret is None:
                    continue
                pending.discard(i)
                logs[i].close()
                if ret != 0 and exit_code == 0:
                    exit_code = ret
                    from ...observability import flight_recorder as _fr
                    _fr.on_fatal("worker_failure", local_rank=i,
                                 exit_code=ret)
                    _terminate_survivors(procs, pending)
            time.sleep(0.2)
        return exit_code
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            if not log.closed:
                log.close()


def launch_elastic(script_args, nproc_per_node=2, max_restarts=3,
                   min_nproc=1, master=None, log_dir="log",
                   env_extra=None, store_dir=None, env_base=None,
                   resume_dir=None):
    """Elastic supervisor: the loop the reference closes in
    `fleet/elastic/manager.py:594` (watch membership -> on scale event,
    tear down, relaunch, resume from checkpoint).

    Each round spawns ``nproc`` workers registered in a
    :class:`~paddle_tpu.distributed.watchdog.FileStore`; a worker death
    deregisters it and the round's :class:`ElasticManager` reports
    ``scale_down``, at which point the survivors are torn down and the
    world relaunches with ``PADDLE_RESTART_COUNT`` bumped — the training
    script resumes from its last checkpoint (`distributed.checkpoint` /
    ``paddle.save``). After a failed retry at the same size the world
    shrinks by one (elastic scale-down) until ``min_nproc``.

    ``resume_dir`` is exported to every generation as
    ``PADDLE_TPU_RESUME_DIR``: a worker that drives a
    :class:`~paddle_tpu.distributed.checkpoint_manager
    .CheckpointManager` (or the hapi ``CheckpointCallback``) on that
    directory resumes at ``latest_step() + 1`` instead of step 0, so a
    relaunch costs at most one save interval of work.

    Returns the final exit code (0 once a round completes cleanly).
    """
    import tempfile

    from ..watchdog import ElasticManager, FileStore

    store_dir = store_dir or tempfile.mkdtemp(prefix="elastic_store_")
    metrics = _launch_metrics()
    restarts = 0
    nproc = int(nproc_per_node)
    while True:
        metrics["world"].set(nproc)
        code = _elastic_round(script_args, nproc, master, log_dir,
                              dict(env_extra or {}), restarts, store_dir,
                              ElasticManager, FileStore, env_base,
                              metrics, resume_dir)
        if code == 0:
            return 0
        restarts += 1
        if restarts > max_restarts:
            return code
        metrics["restarts"].inc()
        if restarts > 1 and nproc > min_nproc:
            nproc -= 1          # repeated failure: shrink the world


def _elastic_round(script_args, nproc, master, log_dir, env_extra,
                   restarts, store_dir, ElasticManager, FileStore,
                   env_base=None, metrics=None, resume_dir=None):
    """One supervised generation: spawn, watch membership, tear down on
    the first scale event."""
    world = nproc
    if world > 1 and master is None:
        master = "127.0.0.1:23459"
    os.makedirs(log_dir, exist_ok=True)
    store = FileStore(store_dir)
    for h in store.hosts():        # a fresh generation starts empty
        store.deregister(h)
    manager = ElasticManager(store, host_id="supervisor",
                             expected_hosts=world)
    procs, logs = [], []
    try:
        for rank in range(world):
            env = dict(os.environ if env_base is None else env_base)
            env.update(env_extra)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_LOCAL_RANK": str(rank),
                "PADDLE_NNODES": "1",
                "PADDLE_RESTART_COUNT": str(restarts),
                "PADDLE_ELASTIC": "1",
                "FLAGS_selected_devices": str(rank),
            })
            if resume_dir:
                env["PADDLE_TPU_RESUME_DIR"] = str(resume_dir)
            if master:
                env["PADDLE_MASTER"] = master
            log = open(os.path.join(log_dir,
                                    f"workerlog.{restarts}.{rank}"), "w")
            logs.append(log)
            store.register(str(rank))
            procs.append(subprocess.Popen(
                [sys.executable] + list(script_args),
                env=env, stdout=log, stderr=subprocess.STDOUT))
        exit_code = 0
        pending = set(range(world))
        while pending:
            for i in sorted(pending):
                ret = procs[i].poll()
                if ret is None:
                    continue
                pending.discard(i)
                logs[i].close()
                store.deregister(str(i))
                if ret != 0:
                    if metrics is not None:
                        metrics["failures"].inc()
                    # supervisor-side post-mortem of the generation: the
                    # dead rank's own recorder (if any) dumped in its
                    # process; this bundle captures the fleet view
                    from ...observability import flight_recorder as _fr
                    _fr.on_fatal("elastic_worker_failure", rank=i,
                                 exit_code=ret, restarts=restarts,
                                 world=world)
                    if exit_code == 0:
                        exit_code = ret
            if exit_code and manager.watch_once() == "scale_down":
                # membership shrank below the expected world: tear down
                # the generation (reference manager.py:594 behavior).
                # Survivors may be parked in a blocking collective that
                # shrugs off SIGTERM — escalate to SIGKILL after a grace
                # period.
                _terminate_survivors(procs, pending)
                for j in pending:
                    logs[j].close()
                    store.deregister(str(j))
                pending.clear()
            time.sleep(0.2)
        return exit_code
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            if not log.closed:
                log.close()


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="launch multi-host paddle_tpu training")
    ap.add_argument("--nnodes", type=int, default=1)
    ap.add_argument("--node_rank", type=int,
                    default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    ap.add_argument("--nproc_per_node", type=int, default=1,
                    help="processes on this host (1 = all local chips in "
                         "one process, the TPU default)")
    ap.add_argument("--master", default=os.environ.get("PADDLE_MASTER"))
    ap.add_argument("--log_dir", default="log")
    ap.add_argument("--elastic", action="store_true",
                    help="supervise with restart-on-failure + scale-down "
                         "(reference fleet/elastic)")
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("--min_nproc", type=int, default=1)
    ap.add_argument("--resume_dir",
                    default=os.environ.get("PADDLE_TPU_RESUME_DIR"),
                    help="checkpoint root exported to workers as "
                         "PADDLE_TPU_RESUME_DIR; an elastic relaunch "
                         "resumes from its latest committed step")
    ap.add_argument("script", nargs=argparse.REMAINDER,
                    help="training script and its arguments")
    args = ap.parse_args(argv)
    if not args.script:
        ap.error("no training script given")
    if args.elastic:
        code = launch_elastic(args.script,
                              nproc_per_node=args.nproc_per_node,
                              max_restarts=args.max_restarts,
                              min_nproc=args.min_nproc,
                              master=args.master, log_dir=args.log_dir,
                              resume_dir=args.resume_dir)
    else:
        code = launch(args.script, nnodes=args.nnodes,
                      node_rank=args.node_rank,
                      nproc_per_node=args.nproc_per_node,
                      master=args.master, log_dir=args.log_dir)
    sys.exit(code)
