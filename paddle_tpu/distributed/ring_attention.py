"""Context parallelism: ring attention + Ulysses all-to-all attention.

Reference (absence): the reference's longest-context mechanisms are
Megatron-SP (`fleet/utils/sequence_parallel_utils.py:395,528`) and the
"sep" axis alltoall redistribution (`meta_parallel/segment_parallel.py:26`)
— it has **no ring attention / blockwise CP in-tree** (SURVEY §5). This
module goes beyond it, per the build plan:

- :func:`ring_attention` — blockwise-softmax attention with K/V chunks
  rotating around the ``cp`` ring via ``lax.ppermute`` (collective-permute
  on the ICI ring). The last rotation is peeled off (no wasted transfer),
  each block update is rematerialized (``jax.checkpoint``) so backward
  memory stays O((S/P)^2) per in-flight block, and with ``causal=True``
  fully-masked future blocks skip their einsums via ``lax.cond``.
  Known limitation: contiguous chunking leaves the causal ring
  load-imbalanced (device 0 has the least work); zigzag/striped sharding
  is the standard follow-up optimization.
- :func:`ulysses_attention` — the alltoall mode (DeepSpeed-Ulysses /
  the reference's "sep" axis): ``lax.all_to_all`` swaps the sharded dim
  from sequence to heads inside ``shard_map``, full-sequence attention
  runs on the local heads (through the Pallas flash kernel when shapes
  allow, the XLA path otherwise), and a second all-to-all swaps back.

Both take ``[B, S, H, D]`` Tensors whose sequence dim is sharded over
``axis``, return outputs with the same sharding, and differentiate
through (``jax.vjp`` through scan/ppermute/all_to_all).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..framework.tensor import run_op
from .process_mesh import ProcessMesh
from .pipeline import shard_map

__all__ = ["ring_attention", "ulysses_attention",
           "zigzag_reorder", "zigzag_restore"]

_NEG = -1e30


@functools.lru_cache(maxsize=64)
def _build_ring(jmesh, axis, causal, scale):
    P = jmesh.shape[axis]
    perm = [(r, (r + 1) % P) for r in range(P)]

    def per_device(q, k, v):
        # local chunks [B, S/P, H(q)/Hk, D]
        i = jax.lax.axis_index(axis)
        b, s_loc, h, d = q.shape
        hk = k.shape[2]
        group = h // hk
        qf = jnp.swapaxes(q, 1, 2).astype(jnp.float32)      # [B, H, Sl, D]
        qpos = i * s_loc + jnp.arange(s_loc, dtype=jnp.int32)

        @jax.checkpoint
        def block(carry, kc, vc, j):
            """Online-softmax update of (acc, m, l) against chunk j."""
            acc, m, l = carry
            kf = jnp.swapaxes(kc, 1, 2).astype(jnp.float32)
            vf = jnp.swapaxes(vc, 1, 2).astype(jnp.float32)
            if group > 1:
                kf = jnp.repeat(kf, group, axis=1)
                vf = jnp.repeat(vf, group, axis=1)
            s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
            if causal:
                kpos = j * s_loc + jnp.arange(s_loc, dtype=jnp.int32)
                mask = qpos[:, None] >= kpos[None, :]
                s = jnp.where(mask[None, None], s, _NEG)
            m_cur = jnp.max(s, axis=-1)                     # [B, H, Sl]
            m_new = jnp.maximum(m, m_cur)
            p = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p, axis=-1)
            acc_new = acc * alpha[..., None] + \
                jnp.einsum("bhqk,bhkd->bhqd", p, vf)
            return acc_new, m_new, l_new

        def update(carry, kc, vc, j):
            if not causal:
                return block(carry, kc, vc, j)
            # a block whose chunk lies entirely in the future is all-masked
            # — skip its einsums (saves ~half the ring's flops)
            return jax.lax.cond(j <= i, lambda c: block(c, kc, vc, j),
                                lambda c: c, carry)

        def step(carry, t):
            kc, vc, state = carry
            state = update(state, kc, vc, (i - t) % P)
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return (kc, vc, state), None

        state = (jnp.zeros((b, h, s_loc, d), jnp.float32),
                 jnp.full((b, h, s_loc), _NEG, jnp.float32),
                 jnp.zeros((b, h, s_loc), jnp.float32))
        # peel the final block: its rotation result would be discarded
        (kc, vc, state), _ = jax.lax.scan(step, (k, v, state),
                                          jnp.arange(P - 1))
        acc, m, l = update(state, kc, vc, (i - (P - 1)) % P)
        out = acc / l[..., None]
        return jnp.swapaxes(out, 1, 2).astype(q.dtype)      # [B, Sl, H, D]

    seq_spec = PartitionSpec(None, axis, None, None)
    inner = shard_map(per_device, mesh=jmesh,
                      in_specs=(seq_spec, seq_spec, seq_spec),
                      out_specs=seq_spec, check_rep=False)
    return jax.jit(inner)


def ring_attention(q, k, v, mesh, axis="sep", causal=True, scale=None,
                   zigzag=False):
    """Blockwise ring attention over the ``axis`` ring. q ``[B, S, H, D]``,
    k/v ``[B, S, Hk, D]`` (GQA native), sequence sharded over ``axis``;
    S must divide by the axis size.

    ``zigzag=True`` (causal only) expects inputs in the zigzag layout
    (:func:`zigzag_reorder`: shard i holds chunk pair (i, 2P-1-i)) and
    balances the causal work across the ring — contiguous sharding
    leaves device 0 mostly idle; zigzag gives every device ~2 sub-blocks
    per rotation. Output stays in zigzag layout
    (:func:`zigzag_restore` undoes it)."""
    jmesh = mesh.to_jax_mesh() if isinstance(mesh, ProcessMesh) else mesh
    P = jmesh.shape[axis]
    qs = q.shape if not hasattr(q, "_data") else q._data.shape
    if qs[1] % P:
        raise ValueError(f"seq {qs[1]} not divisible by ring size {P}")
    d = qs[-1]
    s = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    if zigzag:
        if not causal:
            raise ValueError("zigzag layout only applies to causal rings")
        if qs[1] % (2 * P):
            raise ValueError(
                f"zigzag needs seq {qs[1]} divisible by 2*{P}")
        fn = _build_ring_zigzag(jmesh, axis, s)
        return run_op("ring_attention_zigzag", fn, (q, k, v))
    fn = _build_ring(jmesh, axis, bool(causal), s)
    return run_op("ring_attention", fn, (q, k, v))


@functools.lru_cache(maxsize=64)
def _build_ulysses(jmesh, axis, causal, scale, use_flash):
    from ..nn.functional.attention import _naive_attention
    from ..ops import flash_attention as FA

    def per_device(q, k, v):
        # [B, S/P, H, D] local -> all-to-all -> [B, S, H/P, D] local
        q2 = jax.lax.all_to_all(q, axis, split_axis=2, concat_axis=1,
                                tiled=True)
        k2 = jax.lax.all_to_all(k, axis, split_axis=2, concat_axis=1,
                                tiled=True)
        v2 = jax.lax.all_to_all(v, axis, split_axis=2, concat_axis=1,
                                tiled=True)
        if use_flash and FA.supported(q2, k2, v2, None, causal):
            h, hk = q2.shape[2], k2.shape[2]
            out = FA._make_flash(scale, causal, h // hk)(q2, k2, v2)
        else:
            out = _naive_attention(q2, k2, v2, None, 0.0, causal, None,
                                   scale=scale)
        # heads-sharded -> seq-sharded for the surrounding SP region
        return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    seq_spec = PartitionSpec(None, axis, None, None)
    inner = shard_map(per_device, mesh=jmesh,
                      in_specs=(seq_spec, seq_spec, seq_spec),
                      out_specs=seq_spec, check_rep=False)
    return jax.jit(inner)


def ulysses_attention(q, k, v, mesh, axis="sep", causal=True, scale=None):
    """All-to-all (Ulysses / reference "sep") context parallelism: swap the
    sharded dim from sequence to heads, attend over the full sequence
    locally (flash kernel when eligible), swap back. Requires num (kv)
    heads divisible by the axis size."""
    jmesh = mesh.to_jax_mesh() if isinstance(mesh, ProcessMesh) else mesh
    P = jmesh.shape[axis]
    ks = k.shape if not hasattr(k, "_data") else k._data.shape
    if ks[2] % P:
        raise ValueError(
            f"kv heads {ks[2]} not divisible by sep axis size {P}")
    d = ks[-1]
    s = float(scale) if scale is not None else 1.0 / math.sqrt(d)
    from .. import flags
    fn = _build_ulysses(jmesh, axis, bool(causal), s,
                        bool(flags.flag("use_pallas_kernels")))
    return run_op("ulysses_attention", fn, (q, k, v))


def zigzag_reorder(x, p, axis=1):
    """Permute a [.., S, ..] array so that contiguous shard ``i`` of ``p``
    holds chunk pair ``(i, 2p-1-i)`` of the 2p-way split — the balanced
    layout for causal ring attention (zigzag sharding)."""
    x = jnp.asarray(getattr(x, "_data", x))
    s = x.shape[axis]
    sc = s // (2 * p)
    chunks = jnp.split(x, 2 * p, axis=axis)
    out = []
    for i in range(p):
        out.append(chunks[i])
        out.append(chunks[2 * p - 1 - i])
    return jnp.concatenate(out, axis=axis)


def zigzag_restore(x, p, axis=1):
    """Inverse of :func:`zigzag_reorder`."""
    x = jnp.asarray(getattr(x, "_data", x))
    chunks = jnp.split(x, 2 * p, axis=axis)
    out = [None] * (2 * p)
    for i in range(p):
        out[i] = chunks[2 * i]
        out[2 * p - 1 - i] = chunks[2 * i + 1]
    return jnp.concatenate(out, axis=axis)


@functools.lru_cache(maxsize=64)
def _build_ring_zigzag(jmesh, axis, scale):
    """Causal ring attention over the zigzag layout (device i holds
    chunk pair (i, 2P-1-i)): every device computes ~2 sub-blocks per
    rotation instead of contiguous sharding's 0..P — the standard fix
    for the causal ring's load imbalance (the r4 VERDICT's weak #5;
    the reference has no CP at all, SURVEY §5)."""
    P = jmesh.shape[axis]
    perm = [(r, (r + 1) % P) for r in range(P)]

    def per_device(q, k, v):
        i = jax.lax.axis_index(axis)
        b, s_loc, h, d = q.shape
        hk = k.shape[2]
        group = h // hk
        sc = s_loc // 2
        ar = jnp.arange(sc, dtype=jnp.int32)

        def heads_first(t):
            t = jnp.swapaxes(t, 1, 2).astype(jnp.float32)
            if t.shape[1] != h:
                t = jnp.repeat(t, group, axis=1)
            return t

        qe = heads_first(q[:, :sc])
        ql = heads_first(q[:, sc:])
        pe = i * sc + ar                       # early-chunk positions
        pl = (2 * P - 1 - i) * sc + ar         # late-chunk positions

        @functools.partial(jax.checkpoint, static_argnums=(6,))
        def block(carry, qf, kf, vf, qpos, kpos, masked):
            acc, m, l = carry
            s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
            if masked:
                keep = qpos[:, None] >= kpos[None, :]
                s = jnp.where(keep[None, None], s, _NEG)
            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m, m_cur)
            p_ = jnp.exp(s - m_new[..., None])
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + jnp.sum(p_, axis=-1)
            acc_new = acc * alpha[..., None] + \
                jnp.einsum("bhqk,bhkd->bhqd", p_, vf)
            return acc_new, m_new, l_new

        def step(carry, t):
            kc, vc, se, sl = carry
            j = (i - t) % P
            ke, kl_ = heads_first(kc[:, :sc]), heads_first(kc[:, sc:])
            ve, vl_ = heads_first(vc[:, :sc]), heads_first(vc[:, sc:])
            kpe = j * sc + ar
            kpl = (2 * P - 1 - j) * sc + ar
            # q_late vs k_early: chunk j < P <= 2P-1-i — strictly past,
            # unmasked, every step (the balanced bulk of the work)
            sl = block(sl, ql, ke, ve, pl, kpe, False)
            # q_early vs k_early: only for j <= i (mask on the diagonal)
            se = jax.lax.cond(
                j <= i, lambda c: block(c, qe, ke, ve, pe, kpe, True),
                lambda c: c, se)
            # q_late vs k_late: only for j >= i (mask on the diagonal)
            sl = jax.lax.cond(
                j >= i, lambda c: block(c, ql, kl_, vl_, pl, kpl, True),
                lambda c: c, sl)
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return (kc, vc, se, sl), None

        def init():
            return (jnp.zeros((b, h, sc, d), jnp.float32),
                    jnp.full((b, h, sc), _NEG, jnp.float32),
                    jnp.zeros((b, h, sc), jnp.float32))

        (kc, vc, se, sl), _ = jax.lax.scan(
            step, (k, v, init(), init()), jnp.arange(P - 1))
        # peeled final rotation (t = P-1)
        j = (i - (P - 1)) % P
        ke, kl_ = heads_first(kc[:, :sc]), heads_first(kc[:, sc:])
        ve, vl_ = heads_first(vc[:, :sc]), heads_first(vc[:, sc:])
        kpe = j * sc + ar
        kpl = (2 * P - 1 - j) * sc + ar
        sl = block(sl, ql, ke, ve, pl, kpe, False)
        se = jax.lax.cond(j <= i,
                          lambda c: block(c, qe, ke, ve, pe, kpe, True),
                          lambda c: c, se)
        sl = jax.lax.cond(j >= i,
                          lambda c: block(c, ql, kl_, vl_, pl, kpl, True),
                          lambda c: c, sl)

        def fin(st):
            acc, m, l = st
            return acc / l[..., None]

        out = jnp.concatenate([fin(se), fin(sl)], axis=2)
        return jnp.swapaxes(out, 1, 2).astype(q.dtype)

    seq_spec = PartitionSpec(None, axis, None, None)
    inner = shard_map(per_device, mesh=jmesh,
                      in_specs=(seq_spec, seq_spec, seq_spec),
                      out_specs=seq_spec, check_rep=False)
    return jax.jit(inner)
