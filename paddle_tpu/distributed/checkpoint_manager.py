"""Atomic/async checkpoint manager: the durable half of elastic training.

Reference: `fleet/elastic/manager.py` closes its recovery loop by
checkpointing and relaunching, and `distributed/checkpoint/
save_state_dict.py` defines the sharded on-disk format — but the
reference writes straight into the destination directory, so a crash or
TPU preemption mid-save leaves a torn checkpoint and training restarts
from step 0. :class:`CheckpointManager` makes the save/restore cycle
survivable:

- **Atomic two-phase commit.** Every save writes into
  ``step_<N>.tmp/``, fsyncs data + metadata, writes a ``COMMITTED``
  marker recording each file's size and CRC-32, fsyncs again, and
  ``os.rename``\\ s the directory into place. A reader can never observe
  a half-written ``step_<N>/``: either the rename happened (all files
  durable, checksummed) or the directory is still ``.tmp`` and ignored.
- **Async save.** ``save(..., blocking=False)`` snapshots device arrays
  to host synchronously (the train step is blocked only for the D2H
  copy via :func:`~paddle_tpu.distributed.checkpoint
  .collect_state_shards`) and commits in a background thread; at most
  one write is in flight, and a failed background write surfaces on the
  next :meth:`save`/:meth:`wait`.
- **Retention.** ``max_to_keep`` old committed steps are GC'd after
  each commit — the newest committed step is never removed — and stale
  ``.tmp`` directories from crashed saves are swept.
- **Discovery.** :meth:`latest_step` sees only committed directories;
  :meth:`restore_latest` re-verifies sizes + checksums before loading
  and falls back to the previous committed step when the newest is
  corrupt (each rejection dumps a flight-recorder bundle).
- **Preemption.** :meth:`install_preemption_handler` hooks SIGTERM —
  the TPU preemption notice — for one final blocking emergency save
  before the process exits.

Resume plumbing: ``launch_elastic(resume_dir=...)`` exports
``PADDLE_TPU_RESUME_DIR`` to every worker generation; a worker builds
its manager on that directory and continues from
``restore_latest(...) + 1`` instead of step 0.

Instrumentation (``checkpoint_*`` metrics + ``checkpoint.*`` spans)
goes through ``paddle_tpu.observability`` and is a no-op under
``PADDLE_TPU_METRICS=0``. Fault-injection points (``ckpt.save_begin``,
``ckpt.write``, ``ckpt.before_marker``, ``rename``,
``ckpt.committed``) are wired through
:mod:`paddle_tpu.testing.faults`, so every torn-save case is
exercisable in CI.

Multi-host note: like the reference format, every process writes only
its own shards. This manager assumes ONE committing process per
directory (the single-host launcher case); a multi-host deployment
should barrier before rank 0 commits.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import threading
import time
import zlib

from ..observability import metrics as _om
from ..observability.trace import span as _span
from ..testing import faults as _faults
from . import checkpoint as _ckpt

__all__ = ["CheckpointManager", "CheckpointCorruptError", "RESUME_DIR_ENV",
           "resume_dir_from_env"]

#: the env var ``launch_elastic`` exports so relaunched workers find
#: their checkpoint root
RESUME_DIR_ENV = "PADDLE_TPU_RESUME_DIR"

#: the commit marker file inside a committed step directory
COMMITTED_MARKER = "COMMITTED"

_STEP_RE = re.compile(r"^step_(\d+)$")

#: save-duration buckets: 10ms .. 120s (large sharded writes are slow)
_SAVE_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                 10.0, 30.0, 60.0, 120.0)


def resume_dir_from_env(default=None):
    """The checkpoint root the elastic launcher handed this worker, or
    ``default``."""
    return os.environ.get(RESUME_DIR_ENV, default)


class CheckpointCorruptError(ValueError):
    """A committed step directory failed marker/size/checksum
    verification."""


def _crc32(path, chunk=1 << 20):
    acc = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk)
            if not buf:
                return acc & 0xFFFFFFFF
            acc = zlib.crc32(buf, acc)


def _fsync_dir(path):
    """Best-effort directory fsync (makes the rename itself durable on
    POSIX; some filesystems reject dir fds — never fatal)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointManager:
    """Atomic, optionally async, retention-managed checkpoints under one
    root directory (layout: ``<root>/step_<N>/`` + ``COMMITTED``)."""

    def __init__(self, root, max_to_keep=5, async_save=True,
                 process_index=None):
        self.root = str(root)
        if max_to_keep is not None and int(max_to_keep) < 1:
            raise ValueError("max_to_keep must be >= 1 (the newest "
                             "committed step is never GC'd) or None")
        self.max_to_keep = None if max_to_keep is None else int(max_to_keep)
        self.async_save = bool(async_save)
        self.process_index = process_index
        os.makedirs(self.root, exist_ok=True)
        self._recover_aside()
        self._thread: "threading.Thread | None" = None
        self._error: "BaseException | None" = None
        self._m_saves = _om.counter(
            "checkpoint_saves_total", "checkpoint steps committed")
        self._m_save_failures = _om.counter(
            "checkpoint_save_failures_total",
            "checkpoint saves that failed before commit")
        self._m_save_seconds = _om.histogram(
            "checkpoint_save_seconds",
            "wall time of the write+commit phase",
            buckets=_SAVE_BUCKETS)
        self._m_restores = _om.counter(
            "checkpoint_restores_total", "successful checkpoint restores")
        self._m_restore_failures = _om.counter(
            "checkpoint_restore_failures_total",
            "committed steps rejected during restore "
            "(checksum/size/marker failure)")
        self._m_gc = _om.counter(
            "checkpoint_gc_removed_total",
            "committed steps removed by retention GC")
        self._m_last = _om.gauge(
            "checkpoint_last_committed_step",
            "newest step committed by this process (-1 before the first)")
        self._m_preempt = _om.counter(
            "checkpoint_preemption_saves_total",
            "emergency saves triggered by a preemption signal")

    # -- discovery ------------------------------------------------------
    def step_dir(self, step):
        return os.path.join(self.root, f"step_{int(step):08d}")

    def _recover_aside(self):
        """Heal the one crash window of a same-step re-save: a committed
        ``step_<N>`` moved aside to ``step_<N>.old`` whose replacement
        rename never happened. The aside is a complete committed step —
        promote it back; when the final exists the swap finished, so the
        aside is just garbage."""
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return
        for name in names:
            if not (name.endswith(".old") and _STEP_RE.match(name[:-4])):
                continue
            aside = os.path.join(self.root, name)
            final = os.path.join(self.root, name[:-4])
            if os.path.isdir(final):
                shutil.rmtree(aside, ignore_errors=True)
            else:
                os.rename(aside, final)

    def committed_steps(self):
        """Ascending step numbers whose directory holds a ``COMMITTED``
        marker (``.tmp`` and torn directories never appear here)."""
        out = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for name in names:
            m = _STEP_RE.match(name)
            if m and os.path.exists(os.path.join(self.root, name,
                                                 COMMITTED_MARKER)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self):
        """Newest committed step, or None when the root holds none."""
        steps = self.committed_steps()
        return steps[-1] if steps else None

    def next_step(self):
        """``latest_step() + 1``, or 0 on a fresh root — the step a
        resumed training loop should execute next. Prefer
        ``restore_latest(...) + 1`` when restoring: it reflects the step
        that actually loaded, even if a newer committed step was
        rejected as corrupt."""
        latest = self.latest_step()
        return 0 if latest is None else latest + 1

    # -- save -----------------------------------------------------------
    def save(self, state_dict, step, blocking=None):
        """Atomically commit ``state_dict`` as ``step``.

        Snapshots to host synchronously (the only part that blocks
        training), then writes + commits either inline
        (``blocking=True``) or in a background thread (the default when
        ``async_save``). A pending async save is joined first — at most
        one write is in flight — and any failure it raised surfaces
        here.
        """
        if blocking is None:
            blocking = not self.async_save
        self.wait()
        step = int(step)
        _faults.fire("ckpt.save_begin", step=step)
        with _span("checkpoint.snapshot", step=step):
            proc, meta, data = _ckpt.collect_state_shards(
                state_dict, self.process_index)
        if blocking:
            self._write_and_commit(step, proc, meta, data)
        else:
            t = threading.Thread(
                target=self._write_guarded, args=(step, proc, meta, data),
                name=f"ckpt-save-{step}", daemon=True)
            self._thread = t
            t.start()

    def wait(self):
        """Join the in-flight async save; re-raise its failure, if any."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _write_guarded(self, step, proc, meta, data):
        try:
            self._write_and_commit(step, proc, meta, data)
        except BaseException as e:     # surfaces on the next save()/wait()
            self._error = e

    def _write_and_commit(self, step, proc, meta, data):
        t0 = time.perf_counter()
        try:
            with _span("checkpoint.write", step=step):
                self._commit(step, proc, meta, data)
        except BaseException as e:
            self._m_save_failures.inc()
            from ..observability import flight_recorder as _fr
            _fr.on_fatal("checkpoint_save_failed", e, step=step)
            raise
        self._m_saves.inc()
        self._m_save_seconds.observe(time.perf_counter() - t0)
        self._m_last.set(step)
        self._gc()

    def _commit(self, step, proc, meta, data):
        final = self.step_dir(step)
        tmp = final + ".tmp"
        # a stale tmp (crashed previous attempt) is replaced wholesale;
        # an existing final (re-save of the same step, e.g. an emergency
        # save of an already-committed step) stays in place until the
        # replacement is fully durable — deleting it up front would
        # reopen exactly the torn-save window this class exists to close
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        names = _ckpt.write_state_shards(tmp, proc, meta, data, fsync=True)
        files = {}
        for name in names:
            p = os.path.join(tmp, name)
            files[name] = {"size": os.path.getsize(p), "crc32": _crc32(p)}
        _faults.fire("ckpt.before_marker", step=step)
        marker_path = os.path.join(tmp, COMMITTED_MARKER)
        with open(marker_path, "w") as f:
            json.dump({"step": step, "unix_time": time.time(),
                       "files": files}, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp)
        # the commit point: before this rename the step is invisible,
        # after it the step is complete — there is no in-between. A
        # same-step re-save swaps via an ``.old`` aside (directories
        # can't be rename-replaced atomically); the only crash window is
        # between the two renames, and _recover_aside() heals it by
        # promoting the fully-valid aside back to final.
        old = final + ".old"
        if os.path.isdir(old):
            shutil.rmtree(old)
        if os.path.isdir(final):
            os.rename(final, old)
        _faults.rename(tmp, final, step=step)
        _fsync_dir(self.root)
        if os.path.isdir(old):
            shutil.rmtree(old)
        _faults.fire("ckpt.committed", step=step)

    # -- restore --------------------------------------------------------
    def verify_step(self, step):
        """Raise :class:`CheckpointCorruptError` unless ``step``'s
        directory carries a valid marker and every recorded file matches
        its committed size and CRC-32."""
        d = self.step_dir(step)
        marker_path = os.path.join(d, COMMITTED_MARKER)
        try:
            with open(marker_path) as f:
                marker = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                f"step {step}: unreadable commit marker: {e}") from e
        for name, rec in marker.get("files", {}).items():
            p = os.path.join(d, name)
            if not os.path.exists(p):
                raise CheckpointCorruptError(
                    f"step {step}: committed file {name!r} is missing")
            size = os.path.getsize(p)
            if size != rec["size"]:
                raise CheckpointCorruptError(
                    f"step {step}: {name!r} is {size} bytes, marker "
                    f"recorded {rec['size']}")
            crc = _crc32(p)
            if crc != rec["crc32"]:
                raise CheckpointCorruptError(
                    f"step {step}: {name!r} checksum {crc:#010x} != "
                    f"committed {rec['crc32']:#010x} (corrupt shard?)")

    def restore_latest(self, state_dict):
        """Fill ``state_dict`` in place from the newest restorable
        committed step; returns that step number.

        Uncommitted (``.tmp``/torn) directories are invisible; a
        committed step that fails checksum verification or load is
        skipped (counted + flight-recorder dump) and the previous
        committed step is tried. Returns None when the root holds no
        committed step at all; raises when committed steps exist but
        none restores.
        """
        self._recover_aside()
        steps = self.committed_steps()
        if not steps:
            return None
        last_err = None
        for step in reversed(steps):
            try:
                with _span("checkpoint.restore", step=step):
                    self.verify_step(step)
                    _ckpt.load_state_dict(state_dict, self.step_dir(step))
                self._m_restores.inc()
                return step
            except Exception as e:
                last_err = e
                self._m_restore_failures.inc()
                from ..observability import flight_recorder as _fr
                _fr.on_fatal("checkpoint_restore_failed", e, step=step,
                             root=self.root)
        raise RuntimeError(
            f"no restorable checkpoint under {self.root}: every "
            f"committed step of {steps} failed verification/load; "
            f"last error: {last_err}") from last_err

    # -- retention ------------------------------------------------------
    def _gc(self):
        """Drop committed steps beyond ``max_to_keep`` (newest always
        kept) and sweep stale ``.tmp`` directories. Runs after each
        commit, on the writer thread — never concurrent with a write,
        because saves are single-flight."""
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return
        self._recover_aside()
        for name in names:
            if name.endswith(".tmp") and _STEP_RE.match(name[:-4]):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)
        if self.max_to_keep is None:
            return
        steps = self.committed_steps()
        for step in steps[:-self.max_to_keep]:
            shutil.rmtree(self.step_dir(step), ignore_errors=True)
            self._m_gc.inc()

    # -- preemption -----------------------------------------------------
    def install_preemption_handler(self, state_fn, step_fn,
                                   signals=(signal.SIGTERM,),
                                   exit_code=None):
        """Hook preemption signals (default SIGTERM — what a TPU
        preemption notice and the elastic launcher's teardown both
        deliver) for ONE final blocking emergency save of
        ``state_fn()`` at step ``step_fn()``, then exit with
        ``exit_code`` (default ``128 + signum``, the conventional
        killed-by-signal code). A ``step_fn()`` returning None skips
        the save (nothing has completed that is worth committing —
        saving untrained initial weights would make a relaunch resume
        PAST a step that never ran).

        Must be called from the main thread (CPython signal rule).
        Returns ``{signum: previous_handler}`` so callers can restore.
        """
        prev = {}

        def _handler(signum, frame):
            step = step_fn()
            if step is not None:
                self._m_preempt.inc()
                try:
                    self.save(state_fn(), step, blocking=True)
                except Exception:
                    # exiting anyway — the failure was already counted
                    # and flight-recorded by the save path
                    pass
            os._exit(exit_code if exit_code is not None
                     else 128 + signum)

        for sig in signals:
            prev[sig] = signal.signal(sig, _handler)
        return prev
