"""Distributed sharded checkpointing with resharding-on-load.

Reference: `python/paddle/distributed/checkpoint/save_state_dict.py:104`
(each rank writes its local shards + a global metadata file) and
`load_state_dict.py:247,377` (load computes the overlap between saved
shard boxes and the target placement and copies only the intersecting
regions, so a checkpoint saved on one mesh loads onto ANY other mesh).

Layout on disk:
    path/
      metadata_p{proc}.json    this process's shard index (+ shapes/dtypes)
      shards_p{proc}.npz       this process's local shard data
Load merges every metadata_p*.json it finds, so a multi-host checkpoint
on a shared filesystem reassembles from all processes' shard files.

TPU-native mechanics: shards are ``jax.Array`` addressable shards; the
shard "box" is the global index slice jax reports for each device. On
load the global array is reassembled from the boxes each process can read
and committed to the target sharding with ``jax.device_put`` (GSPMD slices
it back out per device). Multi-host note: every process writes only its
addressable shards; loading reads all shard files it can see — on a
multi-host DCN deployment pair this with a shared filesystem, as the
reference assumes (`save_state_dict.py` writes to a common dir).
"""

from __future__ import annotations

import glob
import json
import os

import jax
import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "collect_state_shards",
           "write_state_shards"]


def _json_safe(v):
    """JSON encoder for numpy scalars/arrays in non-Tensor object values."""
    if isinstance(v, np.ndarray):
        return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
    if isinstance(v, np.generic):
        return v.item()
    raise TypeError(f"checkpoint object value not serializable: {type(v)}")


def _json_restore(v):
    if isinstance(v, dict) and "__ndarray__" in v:
        return np.asarray(v["__ndarray__"], dtype=v["dtype"])
    return v


def _to_numpy(arr):
    a = np.asarray(arr)
    if a.dtype == jnp.bfloat16:
        return a.view(np.uint16), "bfloat16"
    return a, str(a.dtype)


def _from_numpy(a, dtype):
    if dtype == "bfloat16":
        return a.view(jnp.bfloat16)
    return a


def _flatten(state_dict, prefix=""):
    """flat_key -> value, plus flat_key -> (owner dict, key) for writeback."""
    out, owners = {}, {}
    for k, v in state_dict.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            sub, sub_owners = _flatten(v, key)
            out.update(sub)
            owners.update(sub_owners)
        else:
            out[key] = v
            owners[key] = (state_dict, k)
    return out, owners


def collect_state_shards(state_dict, process_index=None):
    """Snapshot ``state_dict`` to host memory: ``(proc, meta, data)``.

    The D2H copy happens HERE (``np.asarray`` of each addressable
    shard), so once this returns the caller may keep mutating the device
    tensors — the synchronous phase of an async checkpoint
    (:class:`~paddle_tpu.distributed.checkpoint_manager
    .CheckpointManager` writes the returned snapshot in a background
    thread).
    """
    flat, _ = _flatten(state_dict)
    proc = jax.process_index() if process_index is None else process_index
    meta = {"tensors": {}}
    data = {}
    for key, t in flat.items():
        if not isinstance(t, Tensor):
            meta.setdefault("objects", {})[key] = t
            continue
        arr = t._data
        entry = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                 "shards": []}
        seen_boxes = set()
        for i, sh in enumerate(arr.addressable_shards):
            box = tuple(
                (0 if idx.start is None else int(idx.start),
                 dim if idx.stop is None else int(idx.stop))
                for idx, dim in zip(sh.index, arr.shape))
            if box in seen_boxes:
                continue  # replicated copies: store once
            seen_boxes.add(box)
            name = f"{key}@{len(entry['shards'])}"
            np_arr, dt = _to_numpy(sh.data)
            data[name] = np_arr
            entry["shards"].append(
                {"box": [list(b) for b in box], "array": name,
                 "file": f"shards_p{proc}.npz", "dtype": dt})
        meta["tensors"][key] = entry
    return proc, meta, data


def write_state_shards(path, proc, meta, data, fsync=False):
    """Write one process's collected snapshot under ``path``; returns
    the file basenames written. With ``fsync=True`` each file is flushed
    to stable storage before returning (the durability half of the
    checkpoint manager's two-phase commit)."""
    from ..testing import faults as _faults

    os.makedirs(path, exist_ok=True)
    shard_name = f"shards_p{proc}.npz"
    meta_name = f"metadata_p{proc}.json"
    shard_path = os.path.join(path, shard_name)
    _faults.fire("ckpt.write", path=shard_path)
    with open(shard_path, "wb") as f:
        np.savez(f, **data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    meta_path = os.path.join(path, meta_name)
    _faults.fire("ckpt.write", path=meta_path)
    with open(meta_path, "w") as f:
        json.dump(meta, f, default=_json_safe)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    return [shard_name, meta_name]


def save_state_dict(state_dict, path, process_index=None):
    """Write each tensor's addressable shards + global metadata.

    Reference: save_state_dict.py:104. ``state_dict`` maps names to
    Tensors (dist or dense; nested dicts flatten with dotted keys).
    Each process writes its OWN metadata slice; load merges them — a
    multi-host checkpoint must index every process's shards.

    NOTE: this writes straight into ``path``; a crash mid-save leaves a
    torn checkpoint. For durable training checkpoints use
    :class:`~paddle_tpu.distributed.checkpoint_manager
    .CheckpointManager`, which wraps this format in an atomic
    two-phase commit.
    """
    proc, meta, data = collect_state_shards(state_dict, process_index)
    write_state_shards(path, proc, meta, data)


def load_state_dict(state_dict, path):
    """Fill ``state_dict``'s tensors IN PLACE from a sharded checkpoint,
    resharding to each tensor's current placement (mesh-to-mesh).

    Reference: load_state_dict.py:377 with the overlap/reshard logic of
    :247 — here reassembly + ``device_put`` to the target sharding lets
    GSPMD do the overlap math.
    """
    flat, owners = _flatten(state_dict)
    meta_files = sorted(glob.glob(os.path.join(path, "metadata_p*.json")))
    if not meta_files:
        raise FileNotFoundError(f"no metadata_p*.json under {path}")
    meta = {"tensors": {}, "objects": {}}
    for mf in meta_files:
        with open(mf) as f:
            m = json.load(f)
        for key, entry in m.get("tensors", {}).items():
            tgt = meta["tensors"].setdefault(
                key, {"shape": entry["shape"], "dtype": entry["dtype"],
                      "shards": []})
            known = {json.dumps(s["box"]) for s in tgt["shards"]}
            for s in entry["shards"]:
                if json.dumps(s["box"]) not in known:
                    tgt["shards"].append(s)
        meta["objects"].update(m.get("objects", {}))
    files = {}

    def shard_data(sh):
        fname = sh["file"]
        if fname not in files:
            files[fname] = np.load(os.path.join(path, fname))
        return _from_numpy(files[fname][sh["array"]], sh["dtype"])

    try:
        missing = []
        for key, t in flat.items():
            if not isinstance(t, Tensor):
                # objects restore by writeback into the owning dict
                if key in meta["objects"]:
                    d, k = owners[key]
                    d[k] = _json_restore(meta["objects"][key])
                else:
                    missing.append(key)
                continue
            entry = meta["tensors"].get(key)
            if entry is None:
                missing.append(key)
                continue
            if list(entry["shape"]) != list(t._data.shape):
                raise ValueError(
                    f"checkpoint tensor {key!r} has shape "
                    f"{entry['shape']}, "
                    f"target expects {list(t._data.shape)}")
            if not entry["shards"]:
                raise ValueError(
                    f"checkpoint tensor {key!r} has no shards in the "
                    f"metadata under {path} — the checkpoint is likely "
                    "incomplete (truncated metadata, or a multi-host "
                    "save missing a process's metadata slice)")
            # reassemble the global array from shard boxes
            full = np.empty(entry["shape"],
                            np.asarray(shard_data(entry["shards"][0])).dtype)
            covered = np.zeros(entry["shape"], dtype=bool)
            for sh in entry["shards"]:
                slices = tuple(slice(b[0], b[1]) for b in sh["box"])
                full[slices] = shard_data(sh)
                covered[slices] = True
            if not covered.all():
                raise ValueError(
                    f"checkpoint for {key!r} does not cover the full "
                    "tensor (multi-host checkpoint loaded without all "
                    "shard files?)")
            arr = jnp.asarray(full)
            # reshard to the tensor's CURRENT placement — the load-time
            # analog of the reference's overlap computation
            sharding = getattr(t._data, "sharding", None)
            if sharding is not None and getattr(t, "is_dist", False):
                arr = jax.device_put(arr, sharding)
            t._data = arr.astype(t._data.dtype)
        if missing:
            raise KeyError(
                f"checkpoint at {path} is missing tensors: {missing[:5]}"
                + ("..." if len(missing) > 5 else ""))
    finally:
        # np.load keeps the zip handle open for lazy member reads; a
        # resume loop that retries restores must not leak one fd per
        # shard file per attempt
        for f in files.values():
            f.close()
    return state_dict
