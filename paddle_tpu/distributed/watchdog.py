"""Failure detection: step watchdog + elastic membership manager.

Reference: the NCCL comm watchdog (`phi/core/distributed/
comm_task_manager.h:37`, timeout detection `comm_task.h:127` — a
background loop that flags hung collectives) and elastic training
(`fleet/elastic/manager.py:124`, watch-loop `:594` — membership
tracking with scale-up/down detection and relaunch).

TPU-native shape: collectives are compiled into the XLA program, so a
hang surfaces as a step that never completes — the watchdog therefore
monitors STEP HEARTBEATS from the host side (the granularity that
exists on TPU), firing a callback / logging / aborting when the gap
exceeds the timeout. ElasticManager tracks expected vs live hosts via a
pluggable store (dict / file-based for tests; etcd-shaped interface)
and reports scale events so a supervisor can checkpoint + relaunch.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

from ..observability import metrics as _om
from ..testing import faults as _faults

__all__ = ["StepWatchdog", "ElasticManager", "FileStore",
           "StaleEpochError"]


class StaleEpochError(RuntimeError):
    """A membership action (heartbeat, registration, request submit or
    completion report) was stamped with an epoch older than the store's
    current epoch for that name: the acting incarnation has been fenced
    out by its supervisor-spawned replacement and must stop — a
    partitioned-but-alive old replica can never race the new one.
    Picklable with its typed fields intact (travels in rpc error
    replies)."""

    def __init__(self, host_id=None, epoch=None, current=None):
        super().__init__(
            f"stale epoch {epoch} for {host_id!r}: the store's current "
            f"epoch is {current} — this incarnation is fenced out by "
            f"its replacement")
        self.host_id = host_id
        self.epoch = epoch
        self.current = current

    def __reduce__(self):
        return (type(self), (self.host_id, self.epoch, self.current))

_WATCHDOG_IDS = itertools.count()
# live instances per label value: two watchdogs given the SAME explicit
# name share one exported child, and the first stop() must not remove a
# series the survivor still updates
_WATCHDOG_REFS_LOCK = threading.Lock()
_WATCHDOG_REFS: dict[str, int] = {}


class StepWatchdog:
    """Host-side hang detector. ``beat()`` after every step; if no beat
    arrives within ``timeout`` seconds, ``on_timeout(gap)`` fires (once
    per stall). Reference analog: CommTaskManager's timeout loop."""

    def __init__(self, timeout=300.0, on_timeout=None, poll=None,
                 abort=False, name=None):
        self.timeout = float(timeout)
        self.on_timeout = on_timeout
        self.abort = abort
        self._poll = poll or min(1.0, self.timeout / 4)
        self._last = None
        self._fired = False
        self._stop = threading.Event()
        self._thread = None
        self.timeouts = 0
        # per-instance label: two watchdogs in one process (train step +
        # data loader) must not zero each other's exported age, so an
        # unnamed instance gets a unique auto label
        self.name = str(name) if name is not None \
            else f"wd{next(_WATCHDOG_IDS)}"
        self._m_timeouts_family = _om.counter(
            "watchdog_timeouts_total", "step-heartbeat stalls detected",
            labelnames=("watchdog",))
        self._m_age_family = _om.gauge(
            "watchdog_heartbeat_age_seconds",
            "seconds since the last step heartbeat",
            labelnames=("watchdog",))
        self._m_timeouts = self._m_timeouts_family.labels(self.name)
        self._m_age = self._m_age_family.labels(self.name)
        self._started = False
        self._stopped = False

    def start(self):
        # the ref is taken here, not in __init__: a constructed-but-
        # abandoned instance must not pin the name forever and block a
        # later same-named watchdog's stop()-time series removal
        if not self._started:
            self._started = True
            with _WATCHDOG_REFS_LOCK:
                _WATCHDOG_REFS[self.name] = \
                    _WATCHDOG_REFS.get(self.name, 0) + 1
            # re-resolve the children: a same-named sibling's stop() may
            # have removed the ones bound at construction, and updates to
            # an orphaned child would never be exported
            self._m_timeouts = self._m_timeouts_family.labels(self.name)
            self._m_age = self._m_age_family.labels(self.name)
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def beat(self):
        self._last = time.monotonic()
        self._fired = False
        self._m_age.set(0.0)

    def arm(self, timeout):
        """Re-aim the running watchdog at one bounded operation: restart
        the clock, THEN set the fresh ``timeout`` — the other order
        lets the poll thread compare the new (small) timeout against a
        stale idle-period heartbeat and fire spuriously. Lets a single
        long-lived instance guard operations whose budget varies call
        to call (e.g. the serving engine's stuck-dispatch detector,
        whose timeout tracks the dispatch-latency P99)."""
        self.beat()
        self.timeout = float(timeout)

    def disarm(self):
        """Stand down between operations: an infinite timeout never
        fires, so idle gaps (an engine waiting for traffic) are not
        stalls. The heartbeat-age gauge keeps exporting."""
        self.timeout = float("inf")
        self.beat()

    def _loop(self):
        while not self._stop.wait(self._poll):
            if self._last is None:
                continue
            gap = time.monotonic() - self._last
            self._m_age.set(gap)
            if self._fired:
                continue
            if gap > self.timeout:
                self._fired = True
                self.timeouts += 1
                self._m_timeouts.inc()
                # post-mortem BEFORE the user callback / abort: a hung
                # rank's last spans, compiles, and metrics are exactly
                # what the stall diagnosis needs
                from ..observability import flight_recorder as _fr
                _fr.on_fatal(f"watchdog_timeout:{self.name}",
                             gap_seconds=gap, timeout=self.timeout)
                if self.on_timeout is not None:
                    self.on_timeout(gap)
                if self.abort:
                    os._exit(124)   # the reference aborts hung workers

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._stopped:
            return
        self._stopped = True
        with _WATCHDOG_REFS_LOCK:
            if self._started:
                remaining = _WATCHDOG_REFS[self.name] = \
                    _WATCHDOG_REFS.get(self.name, 1) - 1
                if remaining <= 0:
                    _WATCHDOG_REFS.pop(self.name, None)
            else:
                remaining = _WATCHDOG_REFS.get(self.name, 0)
        if remaining > 0:
            return      # a same-named sibling still exports this series
        # a stopped watchdog must not keep exporting a frozen heartbeat
        # age (an age > timeout would alert forever); drop zero-count
        # timeout children too so per-fit auto-named instances don't
        # grow label cardinality without bound
        self._m_age_family.remove(self.name)
        if self._m_timeouts.value == 0:
            self._m_timeouts_family.remove(self.name)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class FileStore:
    """Shared-filesystem membership store (the test/simple deployment
    analog of the reference's ETCD registry, which expires leases the
    same way — `fleet/elastic/manager.py` np_etcd lease TTL).

    ``register()`` stamps the current time; with a ``ttl`` (seconds), a
    host whose stamp ages past it stops appearing in :meth:`hosts` — a
    crashed host that never deregistered is treated as dead, and an
    :class:`ElasticManager` watching the store reports ``scale_down``.
    Re-registering (:meth:`heartbeat`) refreshes the stamp.

    Staleness is judged by the stamp file's **mtime** against the fs
    server's own "now" (probed via :meth:`_fs_now`) — one clock every
    writer AND reader agrees on, so neither a skewed writer nor a
    skewed reader (NTP step, drifting VM) can mass-expire perfectly
    healthy hosts. The embedded ``time.time()`` value is kept only as
    a fallback for stores where mtime is unavailable.

    **Epoch fencing (ISSUE 11).** Each host name owns a monotonically
    increasing epoch counter (``.epoch.<host>``, bumped atomically by
    :meth:`next_epoch`). A registration/heartbeat stamped with an
    epoch OLDER than the counter raises a typed
    :class:`StaleEpochError` (and counts
    ``cluster_stale_epoch_rejections_total``): a partitioned-but-alive
    old incarnation whose supervisor already spawned a replacement can
    never resurrect its membership stamp or race the new incarnation —
    the counter survives deregistration, so the fence holds across the
    death/replace window. Heartbeats additionally pass through the
    ``store.heartbeat`` network fault point, so a chaos plan can drop
    or delay them deterministically."""

    #: seconds between fs-clock probes (hosts() scans between probes
    #: reuse the cached offset)
    CLOCK_PROBE_INTERVAL = 5.0

    def __init__(self, path, ttl=None):
        self.path = path
        self.ttl = None if ttl is None else float(ttl)
        os.makedirs(path, exist_ok=True)
        self._clock_probe_at = None     # monotonic stamp of last probe
        self._clock_offset = 0.0        # fs-server now - reader now
        self._m_stale = _om.counter(
            "cluster_stale_epoch_rejections_total",
            "membership/submission actions rejected because their "
            "epoch was fenced out by a newer incarnation")

    def _fs_now(self):
        """The filesystem server's idea of "now". Stamp mtimes come
        from the fs server's clock, so aging must compare them against
        the SAME clock — a reader whose local clock runs ahead would
        otherwise mass-expire every healthy host. Measured by touching
        a hidden probe file and reading its mtime back; the offset is
        cached for CLOCK_PROBE_INTERVAL. Falls back to the local clock
        when the store is not writable."""
        mono = time.monotonic()
        if self._clock_probe_at is None \
                or mono - self._clock_probe_at >= \
                self.CLOCK_PROBE_INTERVAL:
            probe = os.path.join(self.path, f".clock.{os.getpid()}")
            try:
                with open(probe, "w") as f:
                    f.write("x")
                self._clock_offset = os.path.getmtime(probe) \
                    - time.time()
            except OSError:
                self._clock_offset = 0.0
            self._clock_probe_at = mono
        return time.time() + self._clock_offset

    # -- epoch fencing --------------------------------------------------
    def _epoch_path(self, host_id):
        return os.path.join(self.path, f".epoch.{host_id}")

    def epoch_of(self, host_id):
        """The store's current epoch for ``host_id`` (None before the
        first :meth:`next_epoch`)."""
        try:
            with open(self._epoch_path(host_id)) as f:
                return int(f.read().strip() or 0)
        except (OSError, ValueError):
            return None

    def next_epoch(self, host_id, timeout=5.0):
        """Atomically bump and return ``host_id``'s epoch — the fresh
        incarnation's fencing token. Mutual exclusion rides a mkdir
        lock (atomic on shared filesystems). A lock abandoned by a
        crashed bumper is broken only when the lock DIRECTORY itself
        has aged past ``timeout`` (its mtime, not the waiter's
        patience), and breaking is an atomic ``rename`` aside — so two
        impatient waiters can never each remove the other's freshly
        acquired lock and both enter the critical section (which would
        hand out a duplicated epoch and silently defeat the fence)."""
        lock = self._epoch_path(host_id) + ".lock"
        token = f"{os.getpid()}.{time.monotonic_ns()}"
        deadline = time.monotonic() + float(timeout) * 4
        while True:
            try:
                os.mkdir(lock)
                # stamp ownership: a holder stalled past the break
                # timeout must not release a SUCCESSOR's lock from its
                # finally — only the stamped owner may rmdir
                try:
                    with open(os.path.join(lock, "owner"), "w") as f:
                        f.write(token)
                except OSError:
                    pass
                break
            except FileExistsError:
                try:
                    # fs-server clock vs fs mtime: a reader whose local
                    # clock runs ahead of the store must not judge a
                    # LIVE holder's lock stale and break it (two
                    # bumpers in the critical section = one duplicated
                    # epoch = no fence) — same skew discipline as the
                    # heartbeat stamps
                    age = self._fs_now() - os.path.getmtime(lock)
                except OSError:
                    age = 0.0       # vanished: retry the mkdir
                if age > float(timeout):
                    # the holder crashed mid-bump: exactly ONE breaker
                    # wins this atomic rename; everyone (winner
                    # included) then re-competes via mkdir
                    try:
                        os.rename(lock, f"{lock}.stale.{os.getpid()}"
                                        f".{time.monotonic_ns()}")
                    except OSError:
                        pass
                elif time.monotonic() > deadline:
                    break   # wedged store: best-effort bump wins out
                time.sleep(0.01)
            except OSError:
                # read-only store: fall back to a best-effort bump
                break
        try:
            new = (self.epoch_of(host_id) or 0) + 1
            tmp = self._epoch_path(host_id) + f".{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(str(new))
            os.replace(tmp, self._epoch_path(host_id))
            return new
        finally:
            # release ONLY if the lock is still ours: a holder that
            # stalled past the break timeout (its lock renamed aside)
            # or a waiter that gave up without acquiring must not
            # remove a successor's lock
            try:
                owner = os.path.join(lock, "owner")
                with open(owner) as f:
                    still_ours = f.read() == token
                if still_ours:
                    os.remove(owner)
                    os.rmdir(lock)
            except OSError:
                pass
            # sweep locks renamed aside by breakers (dead by
            # definition; best-effort hygiene)
            try:
                for name in os.listdir(self.path):
                    if name.startswith(
                            os.path.basename(lock) + ".stale."):
                        d = os.path.join(self.path, name)
                        try:
                            os.remove(os.path.join(d, "owner"))
                        except OSError:
                            pass
                        os.rmdir(d)
            except OSError:
                pass

    def check_epoch(self, host_id, epoch):
        """Raise :class:`StaleEpochError` (and count the rejection) if
        ``epoch`` has been fenced out by a newer incarnation."""
        if epoch is None:
            return
        current = self.epoch_of(host_id)
        if current is not None and int(epoch) < current:
            self._m_stale.inc()
            raise StaleEpochError(str(host_id), int(epoch), current)

    def register(self, host_id, epoch=None):
        """Stamp ``host_id`` live. With an ``epoch``, the registration
        is FENCED: a stale incarnation raises
        :class:`StaleEpochError` instead of resurrecting its stamp."""
        self.check_epoch(host_id, epoch)
        # stamp atomically (write-aside + replace): open(.., "w") would
        # truncate first, and a concurrent hosts() scan reading the
        # empty file would expire a perfectly healthy host
        final = os.path.join(self.path, str(host_id))
        tmp = os.path.join(self.path, f".stamp.{host_id}.{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(str(time.time()) if epoch is None
                    else f"{time.time()}:{int(epoch)}")
        os.replace(tmp, final)

    def heartbeat(self, host_id, epoch=None):
        """Refresh a live host's timestamp so it outlives the ttl.
        Passes the ``store.heartbeat`` network fault point first — a
        chaos plan can drop (returns False: the beat was lost in the
        network, silently) or delay it. A fenced-out incarnation's
        refresh raises :class:`StaleEpochError`."""
        verdict = _faults.fire_network("store.heartbeat",
                                       src=str(host_id), dst="store")
        if verdict.delay or verdict.hold:
            time.sleep(verdict.delay + verdict.hold)
        if verdict.drop:
            return False
        self.register(host_id, epoch=epoch)
        return True

    def heartbeat_age(self, host_id):
        """Seconds since ``host_id`` last stamped (fs-server clock), or
        None when it has no stamp — the /healthz surface an operator
        reads to spot a fenced-out or silently-aged replica."""
        try:
            stamp = os.path.getmtime(os.path.join(self.path,
                                                  str(host_id)))
        except OSError:
            return None
        return max(0.0, self._fs_now() - stamp)

    def deregister(self, host_id):
        try:
            os.remove(os.path.join(self.path, str(host_id)))
        except FileNotFoundError:
            pass

    def hosts(self):
        now = self._fs_now() if self.ttl is not None else time.time()
        out = []
        for name in sorted(os.listdir(self.path)):
            if name.startswith("."):
                continue            # in-flight stamp writes
            if self.ttl is not None:
                p = os.path.join(self.path, name)
                # age by the stamp file's MTIME first — on a shared
                # filesystem that is the fs server's clock, the one
                # reference all hosts see. The embedded time.time()
                # stamp is the WRITER's clock: cross-host skew or an
                # NTP step there would mass-expire (or immortalize)
                # perfectly healthy replicas, so it is only a fallback
                # for stores where mtime is unavailable/untrustworthy.
                try:
                    stamp = os.path.getmtime(p)
                except OSError:
                    try:
                        with open(p) as f:
                            # stamp content is "ts" or "ts:epoch"
                            stamp = float((f.read().strip() or "0")
                                          .split(":")[0])
                    except (OSError, ValueError):
                        continue        # vanished mid-scan
                if now - stamp > self.ttl:
                    continue
            out.append(name)
        return out


class ElasticManager:
    """Membership watch-loop (reference elastic/manager.py:124).

    ``watch_once()`` compares live membership against the expected world
    and returns one of "normal" / "scale_down" / "scale_up"; ``watch``
    loops until a scale event or stop. A store with a ``ttl`` ages out
    crashed hosts that never deregistered, so a stale registration
    surfaces here as ``scale_down`` rather than a live host forever. A
    supervisor reacts by checkpointing
    (distributed.checkpoint_manager) and relaunching with the new world
    size — the reference's recovery model.
    """

    def __init__(self, store, host_id, expected_hosts,
                 on_scale_event=None):
        self.store = store
        self.host_id = str(host_id)
        self.expected = int(expected_hosts)
        self.on_scale_event = on_scale_event
        self._stop = threading.Event()
        self._m_events = _om.counter(
            "elastic_scale_events_total",
            "membership deviations observed", labelnames=("kind",))
        self._m_live = _om.gauge(
            "elastic_live_hosts", "hosts currently registered")

    def register(self):
        self.store.register(self.host_id)
        return self

    def deregister(self):
        self.store.deregister(self.host_id)

    def watch_once(self):
        live = self.store.hosts()
        self._m_live.set(len(live))
        if len(live) < self.expected:
            self._m_events.labels("scale_down").inc()
            return "scale_down"
        if len(live) > self.expected:
            self._m_events.labels("scale_up").inc()
            return "scale_up"
        return "normal"

    def watch(self, interval=1.0, max_iters=None):
        i = 0
        while not self._stop.is_set():
            state = self.watch_once()
            if state != "normal":
                if self.on_scale_event is not None:
                    self.on_scale_event(state, self.store.hosts())
                return state
            i += 1
            if max_iters is not None and i >= max_iters:
                return "normal"
            time.sleep(interval)
        return "stopped"

    def stop(self):
        self._stop.set()
