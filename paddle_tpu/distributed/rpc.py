"""``paddle.distributed.rpc`` (reference:
`python/paddle/distributed/rpc/rpc.py` — brpc-backed init_rpc /
rpc_sync / rpc_async / shutdown between named workers).

TPU-native transport: the native C++ TCPStore (the control plane's
rendezvous store) instead of brpc — each worker runs a dispatcher
thread that serves requests addressed to its name; calls are pickled
``(caller, call_id, fn, args, kwargs)`` like the reference (plus the
dedup identity). The data plane never touches this path (collectives
ride ICI/DCN inside compiled programs); RPC is for control messages,
metrics, and orchestration — latency budgets where a KV-store
transport is fine.

Partition tolerance (ISSUE 11): the network between caller and callee
is assumed to drop, delay, and duplicate. Delivery is therefore
AT-LEAST-ONCE — a call that times out is retried (bounded, exponential
backoff + jitter) under the SAME ``(caller, call_id)`` identity — and
the dispatcher makes redelivery exactly-once-EFFECTIVE: it remembers
the replies of recently served calls in a bounded cache keyed by that
identity, so a redelivered request republishes the cached reply
instead of executing the handler again (``rpc_duplicate_deliveries_
total`` counts the hits; ``rpc_retries_total`` counts resends).
Deterministic chaos rides :func:`paddle_tpu.testing.faults
.fire_network` at the ``rpc.send`` / ``rpc.reply`` message points.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import os
import pickle
import random
import threading
import time

from ..observability import trace as _otrace
from ..observability import tracing as _tracing
from ..testing import faults as _faults
from .net_store import LeaseStore, StoreUnavailableError

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_current_worker_info", "get_worker_info",
           "get_all_worker_infos", "WorkerInfo", "RpcTimeoutError",
           "RpcEndpoint", "DEFAULT_TIMEOUT_ENV", "IDLE_WAIT_ENV"]

#: env var capping a ``wait(timeout=None)`` on a call that was itself
#: made with ``timeout=None`` — the docstring's "never an indefinite
#: block on a dead peer" holds even when nobody passed a budget
DEFAULT_TIMEOUT_ENV = "PADDLE_TPU_RPC_DEFAULT_TIMEOUT"
_DEFAULT_TIMEOUT = 120.0

#: env var for the default retry budget of rpc_sync / RpcEndpoint.call
#: (attempts = retries + 1); dedup makes retried calls exactly-once-
#: effective, so retrying is safe by default
RETRIES_ENV = "PADDLE_TPU_RPC_RETRIES"
_DEFAULT_RETRIES = 2

#: env var bounding the dispatcher's reply cache (dedup window)
REPLY_CACHE_ENV = "PADDLE_TPU_RPC_REPLY_CACHE"
_DEFAULT_REPLY_CACHE = 512

#: env var for the dispatcher's idle blocking-wait budget per wake.
#: The old idle poll issued a fresh 0.25 s ``get`` four times a second
#: per mailbox; one blocking ``wait`` per budget cuts that control-
#: plane churn ~8x (``store_ops_total{op}`` meters it). Clamped to
#: 2 s so ``stop()`` stays responsive — the wait blocks server-side
#: and can only be abandoned between wakes.
IDLE_WAIT_ENV = "PADDLE_TPU_RPC_IDLE_WAIT"
_DEFAULT_IDLE_WAIT = 2.0


def _env_float(name, default):
    raw = os.environ.get(name)
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _default_rpc_timeout():
    return _env_float(DEFAULT_TIMEOUT_ENV, _DEFAULT_TIMEOUT)


def _default_retries():
    return max(0, int(_env_float(RETRIES_ENV, _DEFAULT_RETRIES)))


def _metrics():
    from ..observability import metrics as _om

    return (_om.counter("rpc_retries_total",
                        "rpc attempts re-sent after a typed timeout"),
            _om.counter("rpc_duplicate_deliveries_total",
                        "redelivered requests answered from the "
                        "dispatcher's reply cache (handler NOT re-run)"))


class RpcTimeoutError(TimeoutError):
    """A synchronous wait on an RPC reply exceeded its ``timeout`` —
    the peer is dead, unreachable, or its handler is stuck. Carries the
    peer name, sequence number and budget so a supervisor can decide to
    retry, reroute, or declare the worker failed instead of blocking
    forever."""

    def __init__(self, to=None, seq=None, timeout=None):
        super().__init__(
            f"rpc to worker {to!r} (seq {seq}) timed out after "
            f"{timeout}s — peer dead or handler stuck")
        self.to = to
        self.seq = seq
        self.timeout = timeout

    def __reduce__(self):
        # a handler's own nested rpc timeout travels back pickled in
        # the error reply; reconstruct from the typed fields, not the
        # formatted message
        return (type(self), (self.to, self.seq, self.timeout))


class WorkerInfo:
    def __init__(self, name, rank):
        self.name = name
        self.rank = rank

    def __repr__(self):
        return f"WorkerInfo(name={self.name!r}, rank={self.rank})"


class _FutureReply:
    def __init__(self, to=None, seq=None, timeout=None):
        self._event = threading.Event()
        self._value = None
        self._error = None
        self._to = to
        self._seq = seq
        self._timeout = timeout

    def _set(self, value, error):
        self._value, self._error = value, error
        self._event.set()

    def wait(self, timeout=None):
        """Block for the reply. ``timeout=None`` falls back to the
        call's own (total, retries-included) timeout; if THAT is also
        None, a default cap (``PADDLE_TPU_RPC_DEFAULT_TIMEOUT``,
        120 s) applies — expiry raises :class:`RpcTimeoutError` (typed
        — never an indefinite block on a dead peer)."""
        if timeout is None:
            timeout = self._timeout
        if timeout is None:
            timeout = _default_rpc_timeout()
        if not self._event.wait(timeout):
            raise RpcTimeoutError(self._to, self._seq, timeout)
        if self._error is not None:
            raise self._error
        return self._value


class _RpcAgent:
    def __init__(self, name, rank, world_size, store, dynamic=False):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        self._stop = threading.Event()
        self._req_seq = 0
        self._serve_from = 0
        # at-least-once identity: every logical call gets one id; a
        # retry reuses it, so the dispatcher can dedup redelivery. The
        # per-agent nonce makes the identity unique ACROSS incarnations
        # of one caller name — a replacement caller's counter restarts
        # at 0, and without the nonce its first calls would hit the
        # dead predecessor's cached replies
        self._incarnation = os.urandom(6).hex()
        self._call_ids = itertools.count()
        # (caller, call_id) -> [reply bytes, last published seq or
        # None]; bounded FIFO — the dedup window
        self._reply_cache: collections.OrderedDict = \
            collections.OrderedDict()
        self._reply_cache_cap = max(
            8, int(_env_float(REPLY_CACHE_ENV, _DEFAULT_REPLY_CACHE)))
        self._m_retries, self._m_dups = _metrics()
        # LeaseStore meters its own store_ops_total{op}; the native
        # TCPStore is ctypes and can't, so the dispatcher counts its
        # idle-loop ops itself — same counter, either backend
        self._m_store_ops = None
        if not isinstance(store, LeaseStore):
            from ..observability import metrics as _om
            self._m_store_ops = _om.counter(
                "store_ops_total",
                "control-plane store client operations",
                labelnames=("op",))
        if dynamic:
            # a REPLACEMENT incarnation of this name must resume the
            # mailbox where the store's seq counter stands — starting at
            # 0 would wait forever on seqs the dead incarnation already
            # consumed (calls addressed to the corpse are lost; their
            # callers time out typed and retry, which is the contract)
            try:
                raw = store.get(f"rpc/seq/{name}", timeout=0.25)
                self._serve_from = int.from_bytes(raw, "little")
            except TimeoutError:
                pass                  # never called: fresh mailbox
            except StoreUnavailableError:
                pass    # store down at join: start at 0; the serve
                # loop resyncs the cursor once the store is back
        self._served = self._serve_from   # dispatcher's next-unserved seq
        if not dynamic:
            store.set(f"rpc/worker/{rank}", name.encode())
        # DEDICATED connection for the dispatcher: a TCPStore client
        # serializes requests on its single socket, so a blocking
        # reply-wait elsewhere must never share the dispatcher's
        # connection — two agents each starving their own dispatcher
        # while waiting on the other is a distributed deadlock
        self._dispatch_store = self._connect()
        self._dispatcher = threading.Thread(target=self._serve, daemon=True)
        self._dispatcher.start()
        self.workers = {}
        if not dynamic:
            # barrier: everyone registered before calls start flying
            store.barrier(world_size, tag="rpc_init")
            for r in range(world_size):
                wname = store.get(f"rpc/worker/{r}", timeout=30).decode()
                self.workers[wname] = WorkerInfo(wname, r)

    def _connect(self):
        # a LeaseStore clones a fresh session to the same lease
        # server; the native TCPStore gets a fresh socket the old way
        clone = getattr(self.store, "clone", None)
        if clone is not None:
            return clone()
        from ..native import TCPStore

        return TCPStore(host=self.store.host, port=self.store.port,
                        timeout=self.store.timeout)

    def _count_op(self, op):
        if self._m_store_ops is not None:
            self._m_store_ops.labels(op).inc()

    def _resync(self, st, seq, streak):
        """The idle wait expired with no message at ``seq``: reconcile
        the cursor against the store's authoritative ``rpc/seq``
        counter. Counter BELOW us -> the store restarted and lost its
        state (new claims start at 0): resume at 0 so the post-restart
        mailbox drains from its bottom — anything re-delivered from
        before the restart hits the dedup cache. Counter ABOVE us with
        our slot still empty across consecutive wakes -> a sender
        claimed the slot and died before publishing: skip the hole
        (safe under at-least-once — its caller times out typed and
        retries under a fresh seq)."""
        try:
            raw = st.get(f"rpc/seq/{self.name}", timeout=0.25)
            claimed = int.from_bytes(raw, "little")
        except StoreUnavailableError:
            return seq, 0
        except TimeoutError:
            return seq, 0       # counter absent: nothing ever claimed
        if claimed < seq:
            return 0, 0
        if claimed > seq:
            streak += 1
            if streak >= 2:
                return seq + 1, 0
            return seq, streak
        return seq, 0

    def _serve(self):
        seq = self._serve_from
        st = self._dispatch_store
        idle_cap = min(2.0, max(0.1, _env_float(IDLE_WAIT_ENV,
                                                _DEFAULT_IDLE_WAIT)))
        missing_streak = 0
        while not self._stop.is_set():
            key = f"rpc/to/{self.name}/{seq}"
            try:
                # one blocking wait per wake replaces the old fresh-
                # 0.25s get poll (see IDLE_WAIT_ENV)
                self._count_op("wait")
                st.wait(key, timeout=idle_cap)
                self._count_op("get")
                payload = st.get(key, timeout=0.25)
            except StoreUnavailableError:
                # store outage: hold the cursor and re-poll — no
                # mailbox slot is skipped, service resumes with the
                # reconnected session
                time.sleep(0.2)
                continue
            except TimeoutError:
                seq, missing_streak = self._resync(st, seq,
                                                   missing_streak)
                continue
            missing_streak = 0
            try:
                st.delete_key(key)
            except StoreUnavailableError:
                pass    # request key leaks until the store's restart
            reply = None
            call_key = None
            caller = None
            try:
                msg = pickle.loads(payload)
                tr = None
                if len(msg) >= 5:
                    # dedup envelope: a redelivered request (network
                    # duplicate, or a retry whose original executed
                    # but whose reply was lost) must NOT run the
                    # handler again — republish the cached reply.
                    # A 6th element is the optional trace context
                    # (absent entirely when the caller traced nothing
                    # — the envelope stays on the pre-trace layout).
                    caller, cid, fn, args, kwargs = msg[:5]
                    tr = msg[5] if len(msg) > 5 else None
                    call_key = (caller, cid)
                    cached = self._reply_cache.get(call_key)
                    if cached is not None:
                        self._m_dups.inc()
                        reply = cached[0]
                        rctx = _tracing.extract(tr)
                        if rctx is not None:
                            # tag the suppressed redelivery in the
                            # trace: a zero-width child of the call
                            # span, so retries that hit the dedup
                            # cache are visible on the timeline
                            with _otrace.span("rpc.dedup",
                                              trace_ctx=rctx.child(),
                                              caller=str(caller),
                                              suppressed=True):
                                pass
                else:
                    fn, args, kwargs = msg      # legacy envelope
                if reply is None:
                    rctx = _tracing.extract(tr)
                    if rctx is None:
                        reply = b"ok:" + pickle.dumps(
                            fn(*args, **kwargs))
                    else:
                        # restore the caller's context: the handler
                        # span (and anything the handler itself
                        # spans or injects downstream) chains to the
                        # remote call span
                        with _tracing.activate(rctx), \
                                _otrace.span(
                                    "rpc.handle",
                                    fn=getattr(fn, "__name__",
                                               str(fn)),
                                    endpoint=str(self.name)):
                            reply = b"ok:" + pickle.dumps(
                                fn(*args, **kwargs))
            except Exception as e:
                reply = b"er:" + pickle.dumps(e)
            if call_key is not None:
                # cache BEFORE the tombstone check: even when a timed-
                # out caller suppressed this publication, its retry
                # must find the result here (exactly-once-effective)
                for stale in self._cache_reply(call_key, reply, seq):
                    try:
                        st.delete_key(f"rpc/reply/{self.name}/{stale}")
                    except StoreUnavailableError:
                        pass
            # (rpc.reply faults fire on the WAITER side — the receiving
            # end of the reply path — where a simulated loss can be
            # cleaned up without leaking tombstones)
            # Tombstone protocol: a timed-out caller plants
            # rpc/dead/{name}/{seq}; consuming it means "don't publish,
            # nobody is waiting" — otherwise a late reply would leak in
            # the master store forever. Re-check after publishing to
            # close the set-between-check-and-publish race (the waiter
            # symmetrically deletes the reply if it was already out).
            reply_key = f"rpc/reply/{self.name}/{seq}"
            tomb_key = f"rpc/dead/{self.name}/{seq}"
            try:
                if not st.delete_key(tomb_key):
                    st.set(reply_key, reply)
                    if st.delete_key(tomb_key):
                        st.delete_key(reply_key)
            except StoreUnavailableError:
                # outage between serve and publish: the reply stays in
                # the dedup cache, so the caller's retry (under the
                # same identity) republishes it — advance the cursor
                pass
            seq += 1
            self._served = seq

    def _cache_reply(self, call_key, reply, seq):
        """Remember a served call's reply for the dedup window and the
        seqs it was published under; returns seqs whose publications
        are now STALE and safe to reap. A publication is never reaped
        right after a newer one lands (the primary's waiter may still
        be mid-read) — only with generations of slack, plus whole
        entries the bounded cache evicts. Dispatcher thread only."""
        stale = []
        entry = self._reply_cache.get(call_key)
        if entry is None:
            self._reply_cache[call_key] = [reply, [seq]]
        else:
            entry[1].append(seq)
            if len(entry[1]) > 4:
                stale.append(entry[1].pop(0))
        self._reply_cache.move_to_end(call_key)
        if len(self._reply_cache) > self._reply_cache_cap:
            _, (_, seqs) = self._reply_cache.popitem(last=False)
            stale.extend(seqs)
        return stale

    def call(self, to, fn, args, kwargs, timeout, retries=None,
             backoff=0.05, backoff_max=2.0):
        """At-least-once call: up to ``retries`` resends (exponential
        backoff + jitter) of the SAME ``(caller, call_id)`` envelope on
        :class:`RpcTimeoutError`; the peer's dedup cache makes the
        retried call exactly-once-effective. ``timeout`` is the
        PER-ATTEMPT reply budget; the returned future's own timeout is
        the total across attempts. Handler exceptions are terminal —
        only transport timeouts retry."""
        if retries is None:
            retries = _default_retries()
        attempts = max(1, int(retries) + 1)
        if timeout is None:
            # defaulting here (not per attempt) keeps the retry
            # contract intact for timeout=None calls: the future's
            # total below covers every attempt, so a sync wait(None)
            # outlives the retries instead of expiring at one
            # attempt's default budget
            timeout = _default_rpc_timeout()
        cid = (self._incarnation, next(self._call_ids))
        env = (self.name, cid, fn, args or (), kwargs or {})
        # trace propagation: with an active context, mint ONE child
        # span for the logical call and append its wire fields as a
        # 6th envelope element. The SAME envelope is re-sent on every
        # retry, so however many deliveries happen, the callee's spans
        # all chain to this one call node. With no active trace (or
        # under PADDLE_TPU_METRICS=0) the envelope stays byte-for-byte
        # on the 5-element pre-trace layout.
        call_ctx = None
        tctx = _tracing.current()
        if tctx is not None:
            call_ctx = tctx.child()
            env = env + (call_ctx.to_wire(),)
        payload = pickle.dumps(env)
        # per-attempt budget + worst-case backoff + slack: the driver
        # thread decides the typed error, wait() is a backstop
        total = attempts * timeout + sum(
            min(backoff_max, backoff * (2 ** i))
            for i in range(attempts - 1)) + 5.0
        fut = _FutureReply(to=to, seq=None, timeout=total)
        fname = getattr(fn, "__name__", str(fn))

        def driver():
            delay = backoff
            last_err = None
            # the driver runs on its own thread (fresh contextvars):
            # record the call span under the exact identity the
            # envelope carries, with per-attempt child spans so
            # retries are visible on the timeline
            call_span = _otrace.span("rpc.call", trace_ctx=call_ctx,
                                     to=str(to), fn=fname) \
                if call_ctx is not None else contextlib.nullcontext()
            try:
                with call_span:
                    for attempt in range(attempts):
                        if attempt:
                            self._m_retries.inc()
                            time.sleep(
                                delay * (1.0 + 0.25 * random.random()))
                            delay = min(backoff_max, delay * 2.0)
                        att_span = _otrace.span(
                            "rpc.attempt", to=str(to),
                            attempt=attempt, retry=bool(attempt)) \
                            if call_ctx is not None \
                            else contextlib.nullcontext()
                        with att_span:
                            err = self._attempt(to, payload, timeout,
                                                fut)
                        if err is None:
                            return      # fut already resolved
                        last_err = err
                        if not isinstance(err, (RpcTimeoutError,
                                                StoreUnavailableError)):
                            break       # terminal: neither a loss nor
                            # a store outage (both of which retry —
                            # the backoff rides out a store restart)
            except Exception as e:      # noqa: BLE001 — a dying driver
                last_err = e            # must resolve, never strand
            fut._set(None, last_err)

        threading.Thread(target=driver, daemon=True).start()
        return fut

    def _attempt(self, to, payload, timeout, fut):
        """One send + reply wait. Resolves ``fut`` and returns None on
        a reply (ok or handler error); returns the transport error
        (``RpcTimeoutError`` = retryable loss) otherwise. Runs on the
        call's driver thread."""
        verdict = _faults.fire_network("rpc.send", src=self.name,
                                       dst=to)
        if timeout is None:
            timeout = _default_rpc_timeout()
        if verdict.drop:
            # the envelope never left this process: no seq claimed, no
            # keys to clean — the loss surfaces as a typed timeout
            return RpcTimeoutError(to, None, timeout)
        deadline = time.monotonic() + timeout
        # per-attempt connection: the blocking reply-get must not pin
        # the shared client (see _dispatch_store note)
        conn = None
        seq = None
        try:
            if verdict.delay:
                time.sleep(verdict.delay)   # in-flight latency: sleep,
                # then claim the mailbox slot (no hole in the mailbox)
            seq = self.store.add(f"rpc/seq/{to}", 1) - 1
            fut._seq = seq
            if verdict.hold:
                # reorder: the slot is claimed but the payload lands
                # late — later messages already queue behind this seq
                time.sleep(verdict.hold)
            self.store.set(f"rpc/to/{to}/{seq}", payload)
            for _ in range(verdict.copies):
                # duplicate delivery: same envelope, its own mailbox
                # slot; the peer's dedup cache suppresses re-execution
                dup = self.store.add(f"rpc/seq/{to}", 1) - 1
                self.store.set(f"rpc/to/{to}/{dup}", payload)
            conn = self._connect()
            remaining = max(0.05, deadline - time.monotonic())
            rsp = conn.get(f"rpc/reply/{to}/{seq}", timeout=remaining)
            conn.delete_key(f"rpc/reply/{to}/{seq}")
            rv = _faults.fire_network("rpc.reply", src=to,
                                      dst=self.name)
            if rv.delay or rv.hold:
                time.sleep(rv.delay + rv.hold)
            if rv.drop:
                # the reply was lost in the network: the handler ran
                # (and cached its reply), we never saw it — retry will
                # hit the peer's dedup cache
                return RpcTimeoutError(to, seq, timeout)
            if rsp[:3] == b"er:":
                fut._set(None, pickle.loads(rsp[3:]))
            else:
                fut._set(pickle.loads(rsp[3:]), None)
            return None
        except Exception as e:
            if isinstance(e, TimeoutError) \
                    and not isinstance(e, RpcTimeoutError):
                # the store's bare TimeoutError means no reply
                # appeared within budget: surface it typed
                e = RpcTimeoutError(to, seq, timeout)
            # Plant a tombstone so the (probably still running)
            # handler skips publishing its reply; if the reply beat
            # the tombstone, reap both keys ourselves. Nothing to
            # plant when the claim itself failed (seq None: no message
            # ever entered the mailbox).
            if conn is not None and seq is not None:
                try:
                    conn.set(f"rpc/dead/{to}/{seq}", b"1")
                    if conn.delete_key(f"rpc/reply/{to}/{seq}"):
                        conn.delete_key(f"rpc/dead/{to}/{seq}")
                except Exception:
                    pass
            return e
        finally:
            if conn is not None:
                conn.close()

    def stop(self):
        self._stop.set()
        self._dispatcher.join(timeout=5)
        # Sweep own tombstones: a timed-out caller plants
        # rpc/dead/{name}/{seq}; the dispatcher consumes it when (not)
        # publishing that seq's reply, so only seqs it never reached —
        # [_served, claimed): shutdown raced the dispatcher, or a
        # crashed caller claimed a seq and never sent — can leak one in
        # the master store forever. Fresh connection: the dispatcher may
        # outlive join(timeout) and still own _dispatch_store's socket.
        start = self._served
        if self._dispatcher.is_alive():
            # the join timed out, so the dispatcher is stuck inside a
            # slow handler for seq _served (after stop() its idle wait
            # can only block ~2s) and will run that seq's tombstone
            # protocol itself when the handler returns — sweeping it
            # here would let the late reply leak instead
            start += 1
        conn = None
        try:
            conn = self._connect()
            try:
                # read-only probe: add(key, 0) would CREATE the seq key
                # for an agent nobody ever called — its own leak
                raw = conn.get(f"rpc/seq/{self.name}", timeout=0.25)
                claimed = int.from_bytes(raw, "little")
            except TimeoutError:
                claimed = start     # never called: nothing to sweep
            for seq in range(start, claimed):
                conn.delete_key(f"rpc/dead/{self.name}/{seq}")
                # the orphaned request payload for an unserved seq is
                # the bigger leak (arbitrary pickled args vs 1 byte)
                conn.delete_key(f"rpc/to/{self.name}/{seq}")
            # reap unconsumed publications the dedup cache still
            # tracks: a duplicate-delivery republish whose waiter was
            # long gone would otherwise leak its reply forever
            for _, pseqs in list(self._reply_cache.values()):
                for pseq in pseqs:
                    conn.delete_key(f"rpc/reply/{self.name}/{pseq}")
        except Exception:
            pass    # best-effort: the store may already be gone
        finally:
            if conn is not None:
                conn.close()
        self._dispatch_store.close()


class RpcEndpoint:
    """A named RPC mailbox with DYNAMIC membership — the serving tier's
    sibling of :func:`init_rpc`'s fixed-world agent.

    ``init_rpc`` assumes a training job: every rank known up front, a
    barrier before the first call, one global agent per process. A
    serving cluster is the opposite — replica processes join when they
    finish compiling, die without notice, and are replaced under a new
    incarnation of the same name — so an endpoint skips the barrier and
    the rank enumeration entirely: the name IS the address (the store
    key-space is already name-keyed: ``rpc/to/{name}/{seq}``), late
    joiners serve as soon as their dispatcher is up, and any number of
    endpoints may live in one process (no global singleton).

    The router hosts the master store (``is_master=True, port=0`` picks
    a free port — read it back from :attr:`port`); workers connect as
    clients. Everything else — the dedicated dispatcher connection, the
    tombstone protocol for timed-out calls, the typed
    :class:`RpcTimeoutError` — is the proven ``_RpcAgent`` machinery,
    reused as-is.
    """

    def __init__(self, name, host="127.0.0.1", port=0, is_master=False,
                 timeout=60.0, store=None):
        self.name = name
        if store is None:
            from ..native import TCPStore

            store = TCPStore(host=host, port=int(port),
                             is_master=is_master, timeout=timeout)
            self.host = host
        else:
            # ride a caller-provided store session — how a TCP-only
            # cluster puts every mailbox on the one LeaseStoreServer
            # (cross-host reachable, outage-tolerant) instead of a
            # per-router native master store
            self.host = store.host
        self.port = store.port
        self._agent = _RpcAgent(name, rank=None, world_size=None,
                                store=store, dynamic=True)
        self._closed = False

    def call(self, to, fn, args=None, kwargs=None, timeout=30.0,
             retries=None):
        """Async call of ``fn(*args, **kwargs)`` on endpoint ``to``;
        returns a future whose ``wait()`` raises the peer's pickled
        exception or a typed :class:`RpcTimeoutError`. ``timeout`` is
        the per-attempt reply budget; a lost request or reply is
        re-sent up to ``retries`` times (default
        ``PADDLE_TPU_RPC_RETRIES``, 2) with exponential backoff +
        jitter — the peer dedups redelivery, so the call stays
        exactly-once-effective."""
        return self._agent.call(to, fn, args, kwargs, timeout,
                                retries=retries)

    def call_sync(self, to, fn, args=None, kwargs=None, timeout=30.0,
                  retries=None):
        # wait(None): the future's own timeout is the retry-inclusive
        # total — bounding the wait by one attempt's budget would kill
        # the call before its retries ran
        return self.call(to, fn, args, kwargs, timeout,
                         retries=retries).wait(None)

    def stop(self):
        """Stop serving and sweep this endpoint's own tombstones.
        Idempotent; the underlying store connection is closed."""
        if self._closed:
            return
        self._closed = True
        self._agent.stop()
        try:
            self._agent.store.close()
        except Exception:
            pass


_agent: _RpcAgent | None = None


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Join the RPC mesh (reference `rpc.py:init_rpc`). Rank 0 hosts the
    store; ``master_endpoint`` is ``"host:port"``."""
    global _agent
    if _agent is not None:
        raise RuntimeError("rpc already initialized")
    import os

    from ..native import TCPStore

    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    endpoint = master_endpoint \
        or os.environ.get("PADDLE_MASTER", "127.0.0.1:0")
    host, port = endpoint.rsplit(":", 1)
    store = TCPStore(host=host, port=int(port), is_master=(rank == 0),
                     timeout=60)
    _agent = _RpcAgent(name, rank, world_size, store)
    return _agent.store.port


def rpc_sync(to, fn, args=None, kwargs=None, timeout=30.0,
             retries=None):
    """Blocking call of ``fn(*args, **kwargs)`` on worker ``to``.

    ``timeout`` (seconds) bounds each delivery attempt; a lost request
    or reply is re-sent up to ``retries`` times (default
    ``PADDLE_TPU_RPC_RETRIES``, 2) with exponential backoff + jitter —
    redelivery is deduped by the peer, so the call stays exactly-once-
    effective. A peer that never answers raises
    :class:`RpcTimeoutError` (a :class:`TimeoutError` subclass naming
    peer/seq/budget) after the bounded total instead of blocking
    forever."""
    return rpc_async(to, fn, args, kwargs, timeout,
                     retries=retries).wait(None)


def rpc_async(to, fn, args=None, kwargs=None, timeout=30.0,
              retries=None):
    """Returns a future with ``.wait()`` (reference returns FutureWrapper)."""
    if _agent is None:
        raise RuntimeError("call init_rpc first")
    return _agent.call(to, fn, args, kwargs, timeout, retries=retries)


def get_current_worker_info():
    return _agent.workers[_agent.name]


def get_worker_info(name):
    return _agent.workers[name]


def get_all_worker_infos():
    return sorted(_agent.workers.values(), key=lambda w: w.rank)


def shutdown():
    """Stop serving (reference `rpc.py:shutdown` barriers first so no
    in-flight call is dropped)."""
    global _agent
    if _agent is None:
        return
    _agent.store.barrier(_agent.world_size, tag="rpc_shutdown")
    _agent.stop()
    _agent.store.close()
    _agent = None
