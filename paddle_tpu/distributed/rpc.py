"""``paddle.distributed.rpc`` (reference:
`python/paddle/distributed/rpc/rpc.py` — brpc-backed init_rpc /
rpc_sync / rpc_async / shutdown between named workers).

TPU-native transport: the native C++ TCPStore (the control plane's
rendezvous store) instead of brpc — each worker runs a dispatcher
thread that serves requests addressed to its name; calls are pickled
``(fn, args, kwargs)`` like the reference. The data plane never touches
this path (collectives ride ICI/DCN inside compiled programs); RPC is
for control messages, metrics, and orchestration — latency budgets
where a KV-store transport is fine.
"""

from __future__ import annotations

import pickle
import threading

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_current_worker_info", "get_worker_info",
           "get_all_worker_infos", "WorkerInfo", "RpcTimeoutError",
           "RpcEndpoint"]


class RpcTimeoutError(TimeoutError):
    """A synchronous wait on an RPC reply exceeded its ``timeout`` —
    the peer is dead, unreachable, or its handler is stuck. Carries the
    peer name, sequence number and budget so a supervisor can decide to
    retry, reroute, or declare the worker failed instead of blocking
    forever."""

    def __init__(self, to=None, seq=None, timeout=None):
        super().__init__(
            f"rpc to worker {to!r} (seq {seq}) timed out after "
            f"{timeout}s — peer dead or handler stuck")
        self.to = to
        self.seq = seq
        self.timeout = timeout

    def __reduce__(self):
        # a handler's own nested rpc timeout travels back pickled in
        # the error reply; reconstruct from the typed fields, not the
        # formatted message
        return (type(self), (self.to, self.seq, self.timeout))


class WorkerInfo:
    def __init__(self, name, rank):
        self.name = name
        self.rank = rank

    def __repr__(self):
        return f"WorkerInfo(name={self.name!r}, rank={self.rank})"


class _FutureReply:
    def __init__(self, to=None, seq=None, timeout=None):
        self._event = threading.Event()
        self._value = None
        self._error = None
        self._to = to
        self._seq = seq
        self._timeout = timeout

    def _set(self, value, error):
        self._value, self._error = value, error
        self._event.set()

    def wait(self, timeout=None):
        """Block for the reply. ``timeout=None`` falls back to the
        call's own timeout; expiry raises :class:`RpcTimeoutError`
        (typed — never an indefinite block on a dead peer)."""
        if timeout is None:
            timeout = self._timeout
        if not self._event.wait(timeout):
            raise RpcTimeoutError(self._to, self._seq, timeout)
        if self._error is not None:
            raise self._error
        return self._value


class _RpcAgent:
    def __init__(self, name, rank, world_size, store, dynamic=False):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        self._stop = threading.Event()
        self._req_seq = 0
        self._serve_from = 0
        if dynamic:
            # a REPLACEMENT incarnation of this name must resume the
            # mailbox where the store's seq counter stands — starting at
            # 0 would wait forever on seqs the dead incarnation already
            # consumed (calls addressed to the corpse are lost; their
            # callers time out typed and retry, which is the contract)
            try:
                raw = store.get(f"rpc/seq/{name}", timeout=0.25)
                self._serve_from = int.from_bytes(raw, "little")
            except TimeoutError:
                pass                  # never called: fresh mailbox
        self._served = self._serve_from   # dispatcher's next-unserved seq
        if not dynamic:
            store.set(f"rpc/worker/{rank}", name.encode())
        # DEDICATED connection for the dispatcher: a TCPStore client
        # serializes requests on its single socket, so a blocking
        # reply-wait elsewhere must never share the dispatcher's
        # connection — two agents each starving their own dispatcher
        # while waiting on the other is a distributed deadlock
        self._dispatch_store = self._connect()
        self._dispatcher = threading.Thread(target=self._serve, daemon=True)
        self._dispatcher.start()
        self.workers = {}
        if not dynamic:
            # barrier: everyone registered before calls start flying
            store.barrier(world_size, tag="rpc_init")
            for r in range(world_size):
                wname = store.get(f"rpc/worker/{r}", timeout=30).decode()
                self.workers[wname] = WorkerInfo(wname, r)

    def _connect(self):
        from ..native import TCPStore

        return TCPStore(host=self.store.host, port=self.store.port,
                        timeout=self.store.timeout)

    def _serve(self):
        seq = self._serve_from
        st = self._dispatch_store
        while not self._stop.is_set():
            key = f"rpc/to/{self.name}/{seq}"
            try:
                payload = st.get(key, timeout=0.25)
            except TimeoutError:
                continue
            st.delete_key(key)
            reply_key = f"rpc/reply/{self.name}/{seq}"
            try:
                fn, args, kwargs = pickle.loads(payload)
                reply = b"ok:" + pickle.dumps(fn(*args, **kwargs))
            except Exception as e:
                reply = b"er:" + pickle.dumps(e)
            # Tombstone protocol: a timed-out caller plants
            # rpc/dead/{name}/{seq}; consuming it means "don't publish,
            # nobody is waiting" — otherwise a late reply would leak in
            # the master store forever. Re-check after publishing to
            # close the set-between-check-and-publish race (the waiter
            # symmetrically deletes the reply if it was already out).
            tomb_key = f"rpc/dead/{self.name}/{seq}"
            if not st.delete_key(tomb_key):
                st.set(reply_key, reply)
                if st.delete_key(tomb_key):
                    st.delete_key(reply_key)
            seq += 1
            self._served = seq

    def call(self, to, fn, args, kwargs, timeout):
        seq = self.store.add(f"rpc/seq/{to}", 1) - 1
        self.store.set(f"rpc/to/{to}/{seq}",
                       pickle.dumps((fn, args or (), kwargs or {})))
        fut = _FutureReply(to=to, seq=seq, timeout=timeout)

        def waiter():
            # per-call connection: the blocking reply-get must not pin
            # the shared client (see _dispatch_store note)
            conn = None
            try:
                conn = self._connect()
                rsp = conn.get(f"rpc/reply/{to}/{seq}", timeout=timeout)
                conn.delete_key(f"rpc/reply/{to}/{seq}")
                if rsp[:3] == b"er:":
                    fut._set(None, pickle.loads(rsp[3:]))
                else:
                    fut._set(pickle.loads(rsp[3:]), None)
            except Exception as e:
                if isinstance(e, TimeoutError) \
                        and not isinstance(e, RpcTimeoutError):
                    # the store's bare TimeoutError means no reply
                    # appeared within budget: surface it typed
                    e = RpcTimeoutError(to, seq, timeout)
                fut._set(None, e)
                # Plant a tombstone so the (probably still running)
                # handler skips publishing its reply; if the reply beat
                # the tombstone, reap both keys ourselves.
                if conn is not None:
                    try:
                        conn.set(f"rpc/dead/{to}/{seq}", b"1")
                        if conn.delete_key(f"rpc/reply/{to}/{seq}"):
                            conn.delete_key(f"rpc/dead/{to}/{seq}")
                    except Exception:
                        pass
            finally:
                if conn is not None:
                    conn.close()

        threading.Thread(target=waiter, daemon=True).start()
        return fut

    def stop(self):
        self._stop.set()
        self._dispatcher.join(timeout=5)
        # Sweep own tombstones: a timed-out caller plants
        # rpc/dead/{name}/{seq}; the dispatcher consumes it when (not)
        # publishing that seq's reply, so only seqs it never reached —
        # [_served, claimed): shutdown raced the dispatcher, or a
        # crashed caller claimed a seq and never sent — can leak one in
        # the master store forever. Fresh connection: the dispatcher may
        # outlive join(timeout) and still own _dispatch_store's socket.
        start = self._served
        if self._dispatcher.is_alive():
            # the join timed out, so the dispatcher is stuck inside a
            # slow handler for seq _served (after stop() its get() can
            # only block 0.25s) and will run that seq's tombstone
            # protocol itself when the handler returns — sweeping it
            # here would let the late reply leak instead
            start += 1
        conn = None
        try:
            conn = self._connect()
            try:
                # read-only probe: add(key, 0) would CREATE the seq key
                # for an agent nobody ever called — its own leak
                raw = conn.get(f"rpc/seq/{self.name}", timeout=0.25)
                claimed = int.from_bytes(raw, "little")
            except TimeoutError:
                claimed = start     # never called: nothing to sweep
            for seq in range(start, claimed):
                conn.delete_key(f"rpc/dead/{self.name}/{seq}")
                # the orphaned request payload for an unserved seq is
                # the bigger leak (arbitrary pickled args vs 1 byte)
                conn.delete_key(f"rpc/to/{self.name}/{seq}")
        except Exception:
            pass    # best-effort: the store may already be gone
        finally:
            if conn is not None:
                conn.close()
        self._dispatch_store.close()


class RpcEndpoint:
    """A named RPC mailbox with DYNAMIC membership — the serving tier's
    sibling of :func:`init_rpc`'s fixed-world agent.

    ``init_rpc`` assumes a training job: every rank known up front, a
    barrier before the first call, one global agent per process. A
    serving cluster is the opposite — replica processes join when they
    finish compiling, die without notice, and are replaced under a new
    incarnation of the same name — so an endpoint skips the barrier and
    the rank enumeration entirely: the name IS the address (the store
    key-space is already name-keyed: ``rpc/to/{name}/{seq}``), late
    joiners serve as soon as their dispatcher is up, and any number of
    endpoints may live in one process (no global singleton).

    The router hosts the master store (``is_master=True, port=0`` picks
    a free port — read it back from :attr:`port`); workers connect as
    clients. Everything else — the dedicated dispatcher connection, the
    tombstone protocol for timed-out calls, the typed
    :class:`RpcTimeoutError` — is the proven ``_RpcAgent`` machinery,
    reused as-is.
    """

    def __init__(self, name, host="127.0.0.1", port=0, is_master=False,
                 timeout=60.0):
        from ..native import TCPStore

        self.name = name
        store = TCPStore(host=host, port=int(port), is_master=is_master,
                         timeout=timeout)
        self.host = host
        self.port = store.port
        self._agent = _RpcAgent(name, rank=None, world_size=None,
                                store=store, dynamic=True)
        self._closed = False

    def call(self, to, fn, args=None, kwargs=None, timeout=30.0):
        """Async call of ``fn(*args, **kwargs)`` on endpoint ``to``;
        returns a future whose ``wait()`` raises the peer's pickled
        exception or a typed :class:`RpcTimeoutError`."""
        return self._agent.call(to, fn, args, kwargs, timeout)

    def call_sync(self, to, fn, args=None, kwargs=None, timeout=30.0):
        return self.call(to, fn, args, kwargs, timeout).wait(timeout)

    def stop(self):
        """Stop serving and sweep this endpoint's own tombstones.
        Idempotent; the underlying store connection is closed."""
        if self._closed:
            return
        self._closed = True
        self._agent.stop()
        try:
            self._agent.store.close()
        except Exception:
            pass


_agent: _RpcAgent | None = None


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Join the RPC mesh (reference `rpc.py:init_rpc`). Rank 0 hosts the
    store; ``master_endpoint`` is ``"host:port"``."""
    global _agent
    if _agent is not None:
        raise RuntimeError("rpc already initialized")
    import os

    from ..native import TCPStore

    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1)) \
        if world_size is None else world_size
    endpoint = master_endpoint \
        or os.environ.get("PADDLE_MASTER", "127.0.0.1:0")
    host, port = endpoint.rsplit(":", 1)
    store = TCPStore(host=host, port=int(port), is_master=(rank == 0),
                     timeout=60)
    _agent = _RpcAgent(name, rank, world_size, store)
    return _agent.store.port


def rpc_sync(to, fn, args=None, kwargs=None, timeout=30.0):
    """Blocking call of ``fn(*args, **kwargs)`` on worker ``to``.

    ``timeout`` (seconds) bounds the synchronous wait: a dead peer or a
    stuck handler raises :class:`RpcTimeoutError` (a
    :class:`TimeoutError` subclass naming peer/seq/budget) instead of
    blocking forever."""
    return rpc_async(to, fn, args, kwargs, timeout).wait(timeout)


def rpc_async(to, fn, args=None, kwargs=None, timeout=30.0):
    """Returns a future with ``.wait()`` (reference returns FutureWrapper)."""
    if _agent is None:
        raise RuntimeError("call init_rpc first")
    return _agent.call(to, fn, args, kwargs, timeout)


def get_current_worker_info():
    return _agent.workers[_agent.name]


def get_worker_info(name):
    return _agent.workers[name]


def get_all_worker_infos():
    return sorted(_agent.workers.values(), key=lambda w: w.rank)


def shutdown():
    """Stop serving (reference `rpc.py:shutdown` barriers first so no
    in-flight call is dropped)."""
    global _agent
    if _agent is None:
        return
    _agent.store.barrier(_agent.world_size, tag="rpc_shutdown")
    _agent.stop()
    _agent.store.close()
    _agent = None
