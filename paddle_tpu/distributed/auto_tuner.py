"""Parallel-config auto-tuner.

Reference: `python/paddle/distributed/auto_tuner/tuner.py:21` (grid
search over dp/mp/pp/sharding/micro-batch configs, pruned by a memory
cost model `memory_cost_model.py`, trial jobs measured and ranked).

TPU-native shape: candidates are mesh factorizations of the chip count;
the memory model estimates per-chip HBM for params/grads/optimizer
state/activations under the candidate's sharding; surviving candidates
are measured by a user-supplied ``trial_fn(config) -> seconds`` (e.g.
timing a few steps of the real compiled train step) and the fastest
wins.
"""

from __future__ import annotations

import itertools
import math

__all__ = ["TuningConfig", "MemoryCostModel", "AutoTuner", "tune",
           "llama_trial_fn", "tune_llama"]


class TuningConfig:
    """One candidate parallel configuration."""

    def __init__(self, dp=1, mp=1, pp=1, sharding=1, micro_batch=None):
        self.dp = dp
        self.mp = mp
        self.pp = pp
        self.sharding = sharding
        self.micro_batch = micro_batch

    @property
    def world(self):
        return self.dp * self.mp * self.pp * self.sharding

    def mesh_shape(self):
        names, shape = [], []
        for n, d in (("pp", self.pp), ("mp", self.mp),
                     ("sharding", self.sharding), ("dp", self.dp)):
            if d > 1:
                names.append(n)
                shape.append(d)
        return names or ["dp"], shape or [1]

    def __repr__(self):
        return (f"TuningConfig(dp={self.dp}, mp={self.mp}, pp={self.pp}, "
                f"sharding={self.sharding}, mbs={self.micro_batch})")


class MemoryCostModel:
    """Per-chip HBM estimate (reference memory_cost_model.py).

    params: total parameter count; hidden/layers/seq/batch describe the
    activation footprint; dtype_bytes: training compute dtype.
    """

    def __init__(self, n_params, hidden_size, num_layers, seq_len,
                 global_batch, dtype_bytes=2, optimizer_factor=12,
                 activation_factor=22):
        self.n_params = n_params
        self.hidden = hidden_size
        self.layers = num_layers
        self.seq = seq_len
        self.batch = global_batch
        self.dtype_bytes = dtype_bytes
        # param + grad + fp32 master + 2 moments (bytes per param)
        self.state_bytes = dtype_bytes * 2 + optimizer_factor
        self.act_factor = activation_factor

    def bytes_per_chip(self, cfg: TuningConfig):
        shard = cfg.mp * cfg.pp * cfg.sharding   # param/state partitioning
        state = self.n_params * self.state_bytes / max(1, shard)
        mbs = cfg.micro_batch or max(1, self.batch // max(1, cfg.dp))
        acts = (self.act_factor * mbs * self.seq * self.hidden
                * self.layers * self.dtype_bytes) / max(1, cfg.mp * cfg.pp)
        return state + acts

    def fits(self, cfg, hbm_bytes):
        return self.bytes_per_chip(cfg) <= hbm_bytes


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


class AutoTuner:
    """Reference tuner.py:21. ``search()`` enumerates, prunes by memory,
    measures with ``trial_fn`` and returns (best, history)."""

    def __init__(self, num_devices, memory_model=None, hbm_bytes=None,
                 max_mp=None, max_pp=None, constraints=None):
        self.n = num_devices
        self.memory_model = memory_model
        self.hbm = hbm_bytes
        self.max_mp = max_mp or num_devices
        self.max_pp = max_pp or num_devices
        self.constraints = constraints or (lambda cfg: True)

    def candidates(self):
        out = []
        for mp, pp in itertools.product(_divisors(self.n),
                                        _divisors(self.n)):
            if mp > self.max_mp or pp > self.max_pp:
                continue
            if mp * pp > self.n or self.n % (mp * pp):
                continue
            for sharding in _divisors(self.n // (mp * pp)):
                dp = self.n // (mp * pp * sharding)
                cfg = TuningConfig(dp=dp, mp=mp, pp=pp, sharding=sharding)
                if self.constraints(cfg):
                    out.append(cfg)
        return out

    def prune(self, cfgs):
        if self.memory_model is None or self.hbm is None:
            return list(cfgs)
        kept = [c for c in cfgs if self.memory_model.fits(c, self.hbm)]
        return kept

    def search(self, trial_fn, max_trials=None):
        """trial_fn(cfg) -> step seconds (raise/inf = infeasible)."""
        cands = self.prune(self.candidates())
        if max_trials:
            cands = cands[:max_trials]
        history = []
        best, best_t = None, float("inf")
        for cfg in cands:
            try:
                t = float(trial_fn(cfg))
            except Exception:
                t = float("inf")
            history.append((cfg, t))
            if t < best_t:
                best, best_t = cfg, t
        return best, history


def tune(num_devices, trial_fn, memory_model=None, hbm_bytes=None,
         **kwargs):
    """One-call convenience wrapper."""
    tuner = AutoTuner(num_devices, memory_model, hbm_bytes, **kwargs)
    return tuner.search(trial_fn)


def llama_trial_fn(model_cfg_kw, global_batch, seq, steps=3):
    """Built-in trial function (VERDICT r4 weak #7 — the reference's
    tuner launches real jobs, `auto_tuner/tuner.py:21`): returns a
    ``trial_fn(cfg) -> seconds`` that builds the candidate's mesh over
    the available devices, shards a Llama with the dp/mp layout
    (`models.llama.shard_llama`), and times a few real compiled train
    steps."""
    import time

    import numpy as np

    def trial(cfg):
        import paddle_tpu as paddle
        from ..models import LlamaConfig, LlamaForCausalLM
        from ..models.llama import shard_llama
        from . import ProcessMesh

        names, shape = cfg.mesh_shape()
        if not names:
            names, shape = ["dp"], [1]
        import jax

        mesh = ProcessMesh(np.arange(cfg.world).reshape(shape).tolist(),
                           dim_names=names)
        paddle.seed(0)
        model = LlamaForCausalLM(LlamaConfig(**model_cfg_kw))
        shard_llama(model, mesh,
                    tp_axis="mp" if cfg.mp > 1 else None,
                    fsdp_axis="sharding" if cfg.sharding > 1 else None)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())

        def step(ids, labels):
            loss, _ = model(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        compiled = paddle.jit.to_static(step, state=[model, opt],
                                        warmup="once")
        rng = np.random.RandomState(0)
        v = model.config.vocab_size
        ids = rng.randint(0, v, (global_batch, seq + 1)).astype(np.int64)
        a = paddle.to_tensor(ids[:, :-1])
        b = paddle.to_tensor(ids[:, 1:])
        compiled(a, b)      # warmup (eager) — materializes accumulators
        compiled(a, b)      # compile
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = compiled(a, b)
        float(loss)         # sync
        return (time.perf_counter() - t0) / steps

    return trial


def tune_llama(model_cfg_kw, global_batch, seq, num_devices=None,
               max_trials=None, **kwargs):
    """End-to-end tuner: grid -> memory prune -> measured trials of the
    real compiled train step -> best TuningConfig. Wires AutoTuner to
    the training stack the way the reference's tuner drives real
    launches."""
    import jax

    n = num_devices or len(jax.devices())
    c = dict(model_cfg_kw)
    h, L = c["hidden_size"], c["num_hidden_layers"]
    inter = c.get("intermediate_size", 4 * h)
    v = c.get("vocab_size", 32000)
    n_params = L * (4 * h * h + 3 * h * inter) + 2 * v * h
    mm = kwargs.pop("memory_model", None) or MemoryCostModel(
        n_params=n_params, hidden_size=h, num_layers=L, seq_len=seq,
        global_batch=global_batch)
    tuner = AutoTuner(n, memory_model=mm, **kwargs)
    return tuner.search(llama_trial_fn(model_cfg_kw, global_batch, seq),
                        max_trials=max_trials)
