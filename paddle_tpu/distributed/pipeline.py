"""Pipeline parallelism as a compiled collective program.

Reference: `python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:149` (1F1B), `:987` (interleave/VPP),
`passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:32`, with stage
p2p in `pp_utils/p2p_communication.py`.

TPU-native design — the schedule IS the program, not a Python runtime:

- Per-stage weights are STACKED on a leading layer axis and sharded over
  the mesh's ``pp`` axis (``Shard(0)``), so each device holds its stage's
  layers. There is no per-rank process, no send/recv runtime, no
  interceptor actors (reference `fleet_executor/`): one SPMD program runs
  on every device.
- ``pipeline_spmd`` runs the classic fill-drain (GPipe) schedule as a
  ``lax.scan`` over ``M + P - 1`` ticks inside ``shard_map``; activations
  hop stages via ``lax.ppermute`` (collective-permute on the ICI ring —
  the hardware path the reference's NCCL send/recv approximates).
- Backward is ``jax.vjp`` through the scan: XLA schedules the reverse
  pipeline automatically. The 1F1B schedule's *memory* benefit is had via
  ``remat=True`` (``jax.checkpoint`` per stage — recompute activations in
  the backward sweep instead of storing M microbatches of them).

The eager p2p primitives this module rides on live in `p2p.py`
(send_forward/send_backward = the edge-truncated ppermute).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec
# the experimental path still accepts check_rep (the jax.shard_map
# replacement renamed it check_vma); silence its deprecation locally
import warnings as _warnings
with _warnings.catch_warnings():
    _warnings.simplefilter("ignore", DeprecationWarning)
    from jax.experimental.shard_map import shard_map

from .process_mesh import ProcessMesh

__all__ = ["pipeline_spmd", "pipeline_1f1b", "stack_stage_params"]


def stack_stage_params(param_trees):
    """Stack a list of per-layer pytrees into one stacked pytree with a
    leading layer axis (the layout ``pipeline_spmd`` shards over pp)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves, axis=0), *param_trees)


def pipeline_spmd(stage_fn, stacked_params, x, *, mesh, axis="pp",
                  num_microbatches, remat=False, num_virtual_stages=1,
                  watch_name="distributed.pipeline_spmd"):
    """Run ``stage_fn`` as a P-stage pipeline over ``num_microbatches``.

    Args:
        stage_fn: ``(stage_params, h) -> h`` where ``stage_params`` leaves
            have leading dim ``L // (P * V)`` (one chunk's layers) and
            ``h`` is one microbatch of activations. Must preserve ``h``'s
            shape. Pass a STABLE function object — the compiled pipeline
            is memoized on its identity.
        stacked_params: pytree of arrays with leading dim L (total
            layers) in LAYER ORDER; this call commits the pp sharding
            (reordering layers for the interleaved layout internally).
        x: ``[B, ...]`` activations; B must divide by num_microbatches.
        mesh: ProcessMesh (or jax Mesh) containing ``axis``.
        remat: checkpoint each stage application (1F1B-like memory:
            activations recompute in the backward sweep instead of M
            microbatches of them being stored).
        watch_name: compile-watch label for this pipeline's programs
            (callers owning a model, e.g. ``LlamaForCausalLMPipe``, pass
            their own so compile metrics attribute to the model).
        num_virtual_stages: V > 1 runs the interleaved (VPP) schedule of
            the reference's ``PipelineParallelWithInterleave``
            (`pipeline_parallel.py:987`): layer chunk ``c`` lives on
            device ``c % P``, activations ride the ``ppermute`` ring V
            times, and the fill/drain bubble shrinks from
            ``(P-1)/(M+P-1)`` to ``(P-1)/(M*V+P-1)``. Requires
            ``L % (P*V) == 0`` and ``M % P == 0``.

    Returns ``[B, ...]`` outputs, replicated over ``axis``.
    """
    jmesh = mesh.to_jax_mesh() if isinstance(mesh, ProcessMesh) else mesh
    P = jmesh.shape[axis]
    M = int(num_microbatches)
    V = int(num_virtual_stages)
    if x.shape[0] % M:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by microbatches {M}")
    flat, treedef = jax.tree_util.tree_flatten(stacked_params)
    L = flat[0].shape[0]
    if L % (P * V):
        raise ValueError(
            f"{L} stacked layers not divisible by {P} stages x {V} chunks")
    if V > 1:
        if M % P:
            raise ValueError(
                f"interleaved schedule needs microbatches ({M}) divisible "
                f"by stages ({P}) — injection groups are P microbatches")
        # reorder layers chunk-major by owner device: device d's chunks
        # are c = d, P+d, 2P+d, ... so Shard(0) hands it [V, lpc] layers
        lpc = L // (P * V)
        order = np.concatenate(
            [np.arange((v * P + d) * lpc, (v * P + d + 1) * lpc)
             for d in range(P) for v in range(V)])
        flat = [p[order] for p in flat]
    run = _build_run(stage_fn, jmesh, axis, M, bool(remat), treedef, V,
                     watch_name)
    return run(tuple(flat), x)


@functools.lru_cache(maxsize=64)
def _build_run(stage_fn, jmesh, axis, M, remat, treedef, V=1,
               watch_name="distributed.pipeline_spmd"):
    """One jitted pipeline program per (stage_fn, mesh, schedule) config —
    shard_map must live under jit (remat inside eager shard_map is
    unsupported), and the cache keeps eager steps from re-lowering."""
    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    P = jmesh.shape[axis]
    n_leaves = treedef.num_leaves
    p_spec = jax.tree_util.tree_unflatten(
        treedef, [PartitionSpec(axis)] * n_leaves)

    def per_device(params_local, xm_local):
        stage = jax.lax.axis_index(axis)
        T = M + P - 1
        mb = xm_local.shape[1]
        perm = [(i, i + 1) for i in range(P - 1)]

        def tick(carry, t):
            h_recv, out = carry
            idx = jnp.clip(t, 0, M - 1)
            x_in = jax.lax.dynamic_index_in_dim(xm_local, idx, 0,
                                                keepdims=False)
            h_in = jnp.where(stage == 0, x_in, h_recv)
            h_out = fn(params_local, h_in)
            # the last stage banks microbatch t-(P-1) once it exists
            widx = jnp.clip(t - (P - 1), 0, M - 1)
            should = jnp.logical_and(stage == P - 1, t >= P - 1)
            cur = jax.lax.dynamic_index_in_dim(out, widx, 0, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(should, h_out, cur), widx, 0)
            if perm:
                h_next = jax.lax.ppermute(h_out, axis, perm)
            else:
                h_next = h_out
            return (h_next, out), None

        init = (jnp.zeros((mb,) + xm_local.shape[2:], xm_local.dtype),
                jnp.zeros_like(xm_local))
        (_, out), _ = jax.lax.scan(tick, init, jnp.arange(T))
        # only the last stage holds real outputs; make them replicated
        out = jax.lax.psum(
            jnp.where(stage == P - 1, out, jnp.zeros_like(out)), axis)
        return out

    def per_device_interleaved(params_local, xm_local):
        """VPP: device d holds V chunks ([V, lpc] leading dims after the
        caller's layer reorder); an activation rides the wraparound ring
        through virtual stages v*P + d. Device d at tick t serves chunk
        ``v = ((t-d)//P) % V``; injection groups of P microbatches make
        the wrapped activation arrive exactly when its next chunk's slot
        opens (collision-free — see the schedule derivation in
        pipeline_spmd's docstring)."""
        stage = jax.lax.axis_index(axis)
        T = M * V + P - 1
        mb = xm_local.shape[1]
        chunked = jax.tree_util.tree_map(
            lambda p: p.reshape((V, p.shape[0] // V) + p.shape[1:]),
            params_local)
        perm = [(i, (i + 1) % P) for i in range(P)]  # wraparound ring

        def tick(carry, t):
            h_recv, out = carry
            rel = t - stage                   # position in my active window
            v = jnp.clip((rel // P) % V, 0, V - 1)
            g = rel // (V * P)                # injection group
            j = rel % P                       # index within the group
            m = jnp.clip(g * P + j, 0, M - 1)
            x_in = jax.lax.dynamic_index_in_dim(xm_local, m, 0,
                                               keepdims=False)
            inject = jnp.logical_and(stage == 0, v == 0)
            h_in = jnp.where(inject, x_in, h_recv)
            params_v = jax.tree_util.tree_map(
                lambda p: jax.lax.dynamic_index_in_dim(
                    p, v, 0, keepdims=False), chunked)
            h_out = fn(params_v, h_in)
            # last device banks chunk V-1 results as they complete
            should = jnp.logical_and(
                jnp.logical_and(stage == P - 1, v == V - 1),
                jnp.logical_and(rel >= 0, rel < M * V))
            cur = jax.lax.dynamic_index_in_dim(out, m, 0, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(should, h_out, cur), m, 0)
            h_next = jax.lax.ppermute(h_out, axis, perm) if P > 1 else h_out
            return (h_next, out), None

        init = (jnp.zeros((mb,) + xm_local.shape[2:], xm_local.dtype),
                jnp.zeros_like(xm_local))
        (_, out), _ = jax.lax.scan(tick, init, jnp.arange(T))
        out = jax.lax.psum(
            jnp.where(stage == P - 1, out, jnp.zeros_like(out)), axis)
        return out

    if V > 1:
        per_device = per_device_interleaved

    inner = shard_map(per_device, mesh=jmesh,
                      in_specs=(p_spec, PartitionSpec()),
                      out_specs=PartitionSpec(), check_rep=False)

    def run(flat_params, x):
        params = jax.tree_util.tree_unflatten(treedef, list(flat_params))
        B = x.shape[0]
        xm = x.reshape((M, B // M) + x.shape[1:])
        y = inner(params, xm)
        return y.reshape((B,) + y.shape[2:])

    from ..observability.compile_watch import watched_jit
    return watched_jit(run, name=watch_name)


def pipeline_1f1b(stage_fn, loss_fn, stacked_params, x, y, *, mesh,
                  axis="pp", num_microbatches):
    """Explicit 1F1B training schedule (reference
    `fleet/meta_parallel/pipeline_parallel.py:149` ``_forward_backward_
    pipeline``; weight-grad split per
    `passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:32`).

    Unlike :func:`pipeline_spmd` (+ outer ``jax.vjp``), the backward is
    part of the schedule: one ``lax.scan`` over ``2M + 2P - 2`` ticks
    where stage ``s`` runs F of microbatch m at tick ``s + 2m`` and B at
    ``2P - 1 - s + 2m`` — forward and backward interleave exactly as in
    the reference's steady state, so each stage stashes at most
    ``P - s`` in-flight microbatch activations (a static ``min(P, M)``
    slot ring buffer) instead of the fill-drain schedule's ``M``. That
    is 1F1B's memory profile, by construction.

    Zero-bubble property: each B tick computes dx (the cotangent the
    upstream stage is waiting for) and dW from one shared VJP; dW has no
    consumer inside the tick, so XLA's latency-hiding scheduler overlaps
    it with the backward ``ppermute`` — the ZB-H1 "W off the critical
    path" move, emitted by the compiler instead of a hand schedule.

    Args:
        stage_fn: ``(stage_params, h) -> h`` (shape-preserving).
        loss_fn: ``(h, labels) -> scalar`` mean loss per microbatch.
        stacked_params: pytree with leading layer dim ``L`` (sharded
            over ``axis``; ``L % P == 0``).
        x: ``[B, ...]`` inputs; y: ``[B, ...]`` labels.

    Returns ``(loss, grads)`` — scalar mean loss (replicated) and a
    grads pytree shaped like ``stacked_params``.
    """
    jmesh = mesh.to_jax_mesh() if isinstance(mesh, ProcessMesh) else mesh
    P = jmesh.shape[axis]
    M = int(num_microbatches)
    if x.shape[0] % M:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by microbatches {M}")
    flat, treedef = jax.tree_util.tree_flatten(stacked_params)
    if flat[0].shape[0] % P:
        raise ValueError(f"{flat[0].shape[0]} layers not divisible by {P}")
    run = _build_1f1b(stage_fn, loss_fn, jmesh, axis, M, treedef)
    return run(tuple(flat), x, y)


@functools.lru_cache(maxsize=64)
def _build_1f1b(stage_fn, loss_fn, jmesh, axis, M, treedef):
    P = jmesh.shape[axis]
    S = min(P, M)                         # 1F1B in-flight stash depth
    n_leaves = treedef.num_leaves
    p_spec = jax.tree_util.tree_unflatten(
        treedef, [PartitionSpec(axis)] * n_leaves)

    def per_device(params_local, xm, ym):
        stage = jax.lax.axis_index(axis)
        mb = xm.shape[1]
        T = 2 * M + 2 * P - 2
        perm_f = [(i, i + 1) for i in range(P - 1)]
        perm_b = [(i + 1, i) for i in range(P - 1)]

        def tick(carry, t):
            h_recv, g_recv, stash, gacc, loss_acc = carry
            # ---- forward lane: F_m at t = stage + 2m -----------------
            # (F and B parities are opposite per stage, so each tick
            # pays for at most ONE of the two lax.cond bodies — the
            # inactive lane contributes zero FLOPs, giving the schedule
            # its 1F1B cost instead of F+B every tick)
            rel_f = t - stage
            f_act = (rel_f >= 0) & (rel_f % 2 == 0) & (rel_f < 2 * M)
            m_f = jnp.clip(rel_f // 2, 0, M - 1)
            x_in = jax.lax.dynamic_index_in_dim(xm, m_f, 0, keepdims=False)
            h_in = jnp.where(stage == 0, x_in, h_recv)
            h_out = jax.lax.cond(
                f_act, lambda h: stage_fn(params_local, h),
                lambda h: jnp.zeros_like(h), h_in)
            slot_f = m_f % S
            cur = jax.lax.dynamic_index_in_dim(stash, slot_f, 0,
                                               keepdims=False)
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_act, h_in, cur), slot_f, 0)
            # ---- backward lane: B_m at t = 2P - 1 - stage + 2m -------
            rel_b = t - (2 * P - 1 - stage)
            b_act = (rel_b >= 0) & (rel_b % 2 == 0) & (rel_b < 2 * M)
            m_b = jnp.clip(rel_b // 2, 0, M - 1)
            h_saved = jax.lax.dynamic_index_in_dim(stash, m_b % S, 0,
                                                   keepdims=False)
            y_in = jax.lax.dynamic_index_in_dim(ym, m_b, 0, keepdims=False)

            def bwd(args):
                h_saved, y_in, g_recv = args
                h_rec, fvjp = jax.vjp(stage_fn, params_local, h_saved)
                loss_m, lvjp = jax.vjp(lambda h: loss_fn(h, y_in), h_rec)
                (ct_loss,) = lvjp(jnp.ones((), loss_m.dtype))
                ct = jnp.where(stage == P - 1, ct_loss, g_recv)
                dp, dx = fvjp(ct)
                return dp, dx, loss_m

            def bwd_zero(args):
                h_saved, y_in, g_recv = args
                return (jax.tree_util.tree_map(jnp.zeros_like,
                                               params_local),
                        jnp.zeros_like(h_saved), jnp.zeros((), jnp.float32))

            dp, dx, loss_m = jax.lax.cond(
                b_act, bwd, bwd_zero, (h_saved, y_in, g_recv))
            gacc = jax.tree_util.tree_map(
                lambda a, d: a + d.astype(a.dtype), gacc, dp)
            loss_acc = loss_acc + jnp.where(
                stage == P - 1, loss_m, 0.0)
            # ---- ride the rings ----------------------------------------
            h_next = jax.lax.ppermute(h_out, axis, perm_f) if perm_f \
                else h_out
            g_next = jax.lax.ppermute(dx, axis, perm_b) if perm_b else dx
            return (h_next, g_next, stash, gacc, loss_acc), None

        zero_h = jnp.zeros((mb,) + xm.shape[2:], xm.dtype)
        init = (zero_h, zero_h,
                jnp.zeros((S,) + zero_h.shape, xm.dtype),
                jax.tree_util.tree_map(
                    lambda p: jnp.zeros(p.shape, jnp.float32),
                    params_local),
                jnp.zeros((), jnp.float32))
        (_, _, _, gacc, loss_acc), _ = jax.lax.scan(
            tick, init, jnp.arange(T))
        loss = jax.lax.psum(loss_acc, axis) / M
        # the objective is the MEAN over microbatches; gacc summed them
        gacc = jax.tree_util.tree_map(lambda g: g / M, gacc)
        return loss, gacc

    inner = shard_map(per_device, mesh=jmesh,
                      in_specs=(p_spec, PartitionSpec(), PartitionSpec()),
                      out_specs=(PartitionSpec(), p_spec),
                      check_rep=False)

    def run(flat_params, x, y):
        params = jax.tree_util.tree_unflatten(treedef, list(flat_params))
        B = x.shape[0]
        xm = x.reshape((M, B // M) + x.shape[1:])
        ym = y.reshape((M, B // M) + y.shape[1:])
        return inner(params, xm, ym)

    from ..observability.compile_watch import watched_jit
    return watched_jit(run, name="distributed.pipeline_1f1b")
