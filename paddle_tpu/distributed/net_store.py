"""TCP-native control plane: lease membership + KV over one socket
server (ISSUE 20, ROADMAP item 4a).

:class:`~paddle_tpu.distributed.watchdog.FileStore` keeps membership
on a shared filesystem — mtime leases, mkdir-locked epoch counters —
which dies with the mount and cannot span hosts that share nothing.
This module is the cross-host replacement:

- :class:`LeaseStoreServer` — a pure-Python threaded socket server
  speaking 4-byte length-prefixed pickled frames, so tier-1 never
  needs g++. It owns the authoritative state: **server-side TTL
  leases** (stamped from the SERVER's monotonic clock — one clock
  every writer and reader agrees on, the TCP analog of FileStore's
  fs-server mtime discipline), **server-fenced epochs** (a
  registration/heartbeat stamped with an epoch older than the
  server's counter is rejected with the same typed, picklable
  :class:`~paddle_tpu.distributed.watchdog.StaleEpochError` — PR 11's
  stale-incarnation contract carries over verbatim), and the
  ``set``/``get``/``add``/``delete_key``/``wait`` KV surface the rpc
  mailboxes ride (``add`` keys hold a little-endian int64, matching
  the native ``TCPStore``). Each boot mints a nonce that travels in
  the session handshake, so clients can tell a reconnect to the same
  server from a reconnect to a RESTARTED one (whose leases, epochs
  and counters are gone). When :func:`paddle_tpu.native.available`,
  the server can additionally front the C++ ``TCPStore`` for the pure
  KV ops (``native_kv=True``): the handshake advertises its port and
  every client routes ``set``/``get``/``add``/``delete_key``/``wait``
  to the C++ fast path while membership stays on the lease server.
  ``python -m paddle_tpu.distributed.net_store --port N`` runs a
  standalone server process (what the chaos tests SIGKILL and restart
  on the same port).

- :class:`LeaseStore` — the client, implementing the full FileStore
  membership contract (``register``/``heartbeat(epoch=)``/``hosts``/
  ``heartbeat_age``/``deregister``/``next_epoch``/``epoch_of``) plus
  the KV surface, so :class:`~paddle_tpu.inference.cluster
  .ServingCluster` and the rpc agents ride either store unchanged.
  Every transport failure maps to a typed, picklable
  :class:`StoreUnavailableError` carrying the server address and the
  op — no bare socket error reaches a serving dispatch path.
  Idempotent ops retry with exponential backoff + jitter;
  non-idempotent ops (``add``, ``next_epoch`` — a blind retry could
  double-claim a mailbox seq or hand out two epochs) fail fast after
  one attempt. A reconnect re-runs the session handshake; a changed
  boot nonce bumps :meth:`restarts` (the signal a replica's heartbeat
  sidecar uses to re-register under a fresh epoch) and counts
  ``store_reconnects_total``. ``store_outage_seconds`` gauges how
  long the server has been continuously unreachable (0 when healthy)
  and ``store_ops_total{op}`` counts every client op — the idle-churn
  meter the rpc dispatcher's blocking-wait satellite is judged by.

Chaos rides the ``store.connect`` / ``store.frame`` socket points
(:func:`paddle_tpu.testing.faults.fire_store`): refuse, reset, hang,
slow, and torn-frame verdicts are applied client-side, so a seeded
plan replays identically and every injected failure takes the same
typed path a real one would.
"""

from __future__ import annotations

import os
import pickle
import random
import socket
import struct
import threading
import time

from ..observability import metrics as _om
from ..testing import faults as _faults
from .watchdog import StaleEpochError

__all__ = ["LeaseStore", "LeaseStoreServer", "StoreUnavailableError",
           "parse_addr"]

#: wire format: 4-byte big-endian frame length, then a pickled tuple
_LEN = struct.Struct("!I")
_MAX_FRAME = 64 * 1024 * 1024

#: env knobs for the client's retry envelope
RETRIES_ENV = "PADDLE_TPU_STORE_RETRIES"
_DEFAULT_RETRIES = 4
_CONNECT_TIMEOUT = 2.0


class StoreUnavailableError(ConnectionError):
    """The control-plane store could not be reached (or the session
    broke mid-operation) after the client's retry budget. Carries the
    server address and the op so a supervisor can tell a store outage
    from a peer death; subclasses :class:`ConnectionError` (hence
    ``OSError``), so existing transport-tolerant ``except OSError``
    paths degrade instead of crashing. Picklable with its typed
    fields intact (travels in rpc error replies)."""

    def __init__(self, addr=None, op=None, detail=None):
        msg = f"store at {addr} unavailable (op {op!r})"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)
        self.addr = addr
        self.op = op
        self.detail = detail

    def __reduce__(self):
        return (type(self), (self.addr, self.op, self.detail))


def parse_addr(addr):
    """``"host:port"`` (or a ``(host, port)`` pair) -> ``(host, int)``."""
    if isinstance(addr, (tuple, list)):
        host, port = addr
        return str(host), int(port)
    host, _, port = str(addr).rpartition(":")
    return host or "127.0.0.1", int(port)


def _client_metrics():
    return (_om.counter("store_ops_total",
                        "control-plane store client operations",
                        labelnames=("op",)),
            _om.counter("store_reconnects_total",
                        "store sessions re-established after a "
                        "transport failure"),
            _om.gauge("store_outage_seconds",
                      "seconds the control-plane store has been "
                      "continuously unreachable (0 when healthy)"))


def _m_stale():
    return _om.counter(
        "cluster_stale_epoch_rejections_total",
        "membership/submission actions rejected because their epoch "
        "was fenced out by a newer incarnation")


# ---------------------------------------------------------------------
# server
# ---------------------------------------------------------------------
class LeaseStoreServer:
    """Authoritative lease/epoch/KV state behind one listening socket.

    One handler thread per connection; every op runs under one lock
    against plain dicts, with a condition variable waking blocking
    ``get``/``wait`` ops when a key lands — the whole server is a few
    hundred lines of stdlib, deliberately, so the pure-Python path is
    what tier-1 exercises everywhere. Lease stamps and ages come from
    ``time.monotonic()`` IN THIS PROCESS: a skewed client clock can
    neither expire a healthy host nor immortalize a dead one.
    """

    def __init__(self, port=0, host="127.0.0.1", native_kv=False):
        self.host = host
        self._boot = os.urandom(8).hex()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._kv: dict[str, bytes] = {}
        self._leases: dict[str, float] = {}     # host -> monotonic stamp
        self._epochs: dict[str, int] = {}       # survives deregister
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: set[socket.socket] = set()
        self._native = None
        self.native_port = None
        if native_kv:
            from .. import native
            if native.available():
                # the C++ TCPStore fronts the pure KV ops; membership
                # stays here (leases/epochs need the fence + TTL the
                # native server does not implement)
                self._native = native.TCPStore(is_master=True, port=0)
                self.native_port = self._native.port
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"lease-store-{self.port}")
        self._accept_thread.start()

    # -- plumbing -------------------------------------------------------
    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return              # closed
            with self._lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stop.is_set():
                hdr = self._recv_exact(conn, _LEN.size)
                if hdr is None:
                    return
                (n,) = _LEN.unpack(hdr)
                if n > _MAX_FRAME:
                    return
                body = self._recv_exact(conn, n)
                if body is None:
                    return
                try:
                    req = pickle.loads(body)
                    rsp = ("ok", self._dispatch(req))
                except TimeoutError:
                    rsp = ("timeout", None)
                except Exception as e:  # noqa: BLE001 — typed to client
                    rsp = ("err", e)
                out = pickle.dumps(rsp, protocol=pickle.HIGHEST_PROTOCOL)
                conn.sendall(_LEN.pack(len(out)) + out)
        except OSError:
            pass
        finally:
            with self._lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _recv_exact(conn, n):
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- ops ------------------------------------------------------------
    def _dispatch(self, req):
        op, args = req[0], req[1:]
        return getattr(self, f"_op_{op}")(*args)

    def _op_hello(self):
        return {"boot": self._boot, "native_port": self.native_port}

    def _op_ping(self):
        return True

    def _op_set(self, key, value):
        with self._cond:
            self._kv[str(key)] = bytes(value)
            self._cond.notify_all()
        return True

    def _op_get(self, key, timeout):
        key = str(key)
        deadline = time.monotonic() + max(0.0, float(timeout))
        with self._cond:
            while key not in self._kv:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(key)
                self._cond.wait(remaining)
            return self._kv[key]

    def _op_wait(self, key, timeout):
        self._op_get(key, timeout)
        return True

    def _op_add(self, key, delta):
        key = str(key)
        with self._cond:
            cur = int.from_bytes(self._kv.get(key, b"\0" * 8),
                                 "little", signed=True)
            new = cur + int(delta)
            self._kv[key] = new.to_bytes(8, "little", signed=True)
            self._cond.notify_all()
            return new

    def _op_del(self, key):
        with self._cond:
            return self._kv.pop(str(key), None) is not None

    def _op_numkeys(self):
        with self._lock:
            return len(self._kv)

    def _check_epoch(self, host_id, epoch):
        if epoch is None:
            return
        current = self._epochs.get(host_id)
        if current is not None and int(epoch) < current:
            raise StaleEpochError(host_id, int(epoch), current)

    def _op_register(self, host_id, epoch):
        host_id = str(host_id)
        with self._lock:
            self._check_epoch(host_id, epoch)
            if epoch is not None:
                # adopt-max healing: after a server restart the
                # counter is gone, so the first fenced stamp that
                # arrives re-establishes the fence at ITS epoch — a
                # later beat from an older incarnation is still
                # rejected, exactly as before the restart
                self._epochs[host_id] = max(
                    self._epochs.get(host_id, 0), int(epoch))
            self._leases[host_id] = time.monotonic()
        return True

    def _op_heartbeat(self, host_id, epoch):
        return self._op_register(host_id, epoch)

    def _op_hb_age(self, host_id):
        with self._lock:
            stamp = self._leases.get(str(host_id))
        if stamp is None:
            return None
        return max(0.0, time.monotonic() - stamp)

    def _op_dereg(self, host_id):
        with self._lock:
            self._leases.pop(str(host_id), None)
        return True

    def _op_hosts(self, ttl):
        now = time.monotonic()
        with self._lock:
            if ttl is None:
                return sorted(self._leases)
            return sorted(h for h, stamp in self._leases.items()
                          if now - stamp <= float(ttl))

    def _op_next_epoch(self, host_id):
        host_id = str(host_id)
        with self._lock:
            new = self._epochs.get(host_id, 0) + 1
            self._epochs[host_id] = new
            return new

    def _op_epoch_of(self, host_id):
        with self._lock:
            return self._epochs.get(str(host_id))

    # -- lifecycle ------------------------------------------------------
    def stop(self):
        self._stop.set()
        try:
            # shutdown BEFORE close: close() alone leaves the accept
            # thread blocked in its syscall, which keeps the LISTEN
            # socket alive kernel-side — and a same-port restart (the
            # chaos drill) would fail its bind until the next
            # connection attempt happened to wake it
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = list(self._conns), set()
        for conn in conns:
            # active sessions must see the death too — a handler
            # blocked in recv would otherwise serve one more op. RST
            # (linger 0) rather than FIN: a graceful close would park
            # the port in FIN_WAIT until every client noticed, and a
            # same-port restart — the whole point of the chaos drills —
            # would fail its bind
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                struct.pack("ii", 1, 0))
            except OSError:
                pass
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        with self._cond:
            self._cond.notify_all()
        if self._native is not None:
            self._native.close()
            self._native = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


# ---------------------------------------------------------------------
# client
# ---------------------------------------------------------------------
#: ops safe to retry blindly — re-running them converges to the same
#: state. ``add`` / ``next_epoch`` are NOT here: the op may have
#: executed before the reply was lost, and a blind resend would
#: double-claim a seq / hand out a second epoch.
_IDEMPOTENT = frozenset({
    "hello", "ping", "set", "get", "wait", "del", "numkeys",
    "register", "heartbeat", "hb_age", "dereg", "hosts", "epoch_of",
})


class LeaseStore:
    """Client for a :class:`LeaseStoreServer` — the TCP drop-in for
    :class:`~paddle_tpu.distributed.watchdog.FileStore` (membership)
    plus the native ``TCPStore`` (KV), behind one reconnecting
    session. See the module docstring for the failure model.

    Args:
        addr: ``"host:port"`` of the server (or a ``(host, port)``
            pair).
        ttl: membership TTL seconds — sent with each :meth:`hosts`
            scan; AGING is judged by the server's clock.
        timeout: default budget for blocking ``get``/``wait``.
        retries: resend budget for idempotent ops (attempts =
            retries + 1); default ``PADDLE_TPU_STORE_RETRIES`` (4).
    """

    def __init__(self, addr, ttl=None, timeout=30.0, retries=None,
                 backoff=0.05, backoff_max=1.0):
        self.host, self.port = parse_addr(addr)
        self.addr = f"{self.host}:{self.port}"
        self.ttl = None if ttl is None else float(ttl)
        self.timeout = float(timeout)
        if retries is None:
            raw = os.environ.get(RETRIES_ENV)
            retries = int(raw) if raw else _DEFAULT_RETRIES
        self.retries = max(0, int(retries))
        self.backoff = float(backoff)
        self.backoff_max = float(backoff_max)
        self._lock = threading.RLock()
        self._sock = None
        self._boot = None           # server boot nonce of this session
        self._restarts = 0          # distinct server boots seen - 1
        self._native = None         # native KV offload client
        self._native_port = None
        self._op_seq = 0
        self._outage_t0 = None
        self._m_ops, self._m_reconnects, self._m_outage = \
            _client_metrics()
        self._m_stale = _m_stale()

    # -- session --------------------------------------------------------
    def clone(self):
        """A fresh client session to the same server (its own socket —
        what the rpc agents use for their dedicated dispatcher /
        per-attempt connections)."""
        return LeaseStore((self.host, self.port), ttl=self.ttl,
                          timeout=self.timeout, retries=self.retries,
                          backoff=self.backoff,
                          backoff_max=self.backoff_max)

    def restarts(self):
        """How many times this client has observed the server come up
        with a NEW boot nonce (0 until the first restart) — the
        replica heartbeat sidecar's cue to re-register under a fresh
        epoch."""
        with self._lock:
            return self._restarts

    def outage_age(self):
        """Seconds since this client's first unanswered transport
        attempt of the CURRENT outage (0 while healthy). Lock-free
        read: the router's admission gate polls it while other threads
        are mid-retry inside the session lock."""
        t0 = self._outage_t0
        return 0.0 if t0 is None else max(0.0, time.monotonic() - t0)

    def _apply_verdict(self, verdict, what):
        if verdict.slow:
            time.sleep(verdict.slow)
        if verdict.hang:
            time.sleep(verdict.hang)
            raise socket.timeout(f"fault injected: {what} hang")
        if verdict.refuse:
            raise ConnectionRefusedError(
                f"fault injected: {what} refused")
        if verdict.reset:
            raise ConnectionResetError(f"fault injected: {what} reset")
        if verdict.torn:
            raise ConnectionResetError(
                f"fault injected: torn frame at {what}")

    def _ensure_session(self):
        """Connect + handshake (caller holds the lock). Raises OSError
        family on failure; the retry loop owns mapping/backoff."""
        if self._sock is not None:
            return
        self._apply_verdict(
            _faults.fire_store("store.connect", path=self.addr),
            "connect")
        sock = socket.create_connection(
            (self.host, self.port),
            timeout=min(_CONNECT_TIMEOUT, self.timeout))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock
        try:
            hello = self._roundtrip("hello", (), self.timeout)
        except BaseException:
            self._drop_session()
            raise
        reconnected = self._boot is not None
        if hello["boot"] != self._boot:
            if self._boot is not None:
                # a NEW boot: leases, epochs and counters are gone —
                # the owner of this session must re-register
                self._restarts += 1
            self._boot = hello["boot"]
        self._native_port = hello.get("native_port")
        if reconnected:
            self._m_reconnects.inc()
        if self._outage_t0 is not None:
            self._outage_t0 = None
            self._m_outage.set(0.0)
        if self._native_port is not None and self._native is None:
            from .. import native
            if native.available():
                self._native = native.TCPStore(
                    host=self.host, port=self._native_port,
                    timeout=self.timeout)

    def _drop_session(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        native, self._native = self._native, None
        if native is not None:
            try:
                native.close()
            except Exception:
                pass

    def _roundtrip(self, op, args, timeout):
        """One framed request/response on the live socket (caller
        holds the lock; session established)."""
        self._apply_verdict(
            _faults.fire_store("store.frame", step=self._op_seq,
                               path=op), op)
        self._op_seq += 1
        payload = pickle.dumps((op,) + tuple(args),
                               protocol=pickle.HIGHEST_PROTOCOL)
        sock = self._sock
        # the server may legitimately hold a blocking get/wait for the
        # full requested timeout; pad the socket budget past it
        sock.settimeout(max(0.1, float(timeout)) + 5.0)
        sock.sendall(_LEN.pack(len(payload)) + payload)
        hdr = self._recv_exact(sock, _LEN.size)
        (n,) = _LEN.unpack(hdr)
        if n > _MAX_FRAME:
            raise ConnectionResetError(f"oversized frame ({n} bytes)")
        status, value = pickle.loads(self._recv_exact(sock, n))
        if status == "timeout":
            raise TimeoutError(
                f"store op {op!r} timed out after {timeout}s")
        if status == "err":
            if isinstance(value, StaleEpochError):
                self._m_stale.inc()
            raise value
        return value

    @staticmethod
    def _recv_exact(sock, n):
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionResetError("store connection closed")
            buf += chunk
        return buf

    def _call(self, op, *args, timeout=None):
        """Run one op with the retry/reconnect envelope. Transport
        failures surface as :class:`StoreUnavailableError`; a blocking
        op that merely found no key raises bare ``TimeoutError``
        (matching the native store); server-side typed errors
        (:class:`StaleEpochError`) propagate as themselves."""
        if _om.enabled():
            self._m_ops.labels(op).inc()
        if timeout is None:
            timeout = self.timeout
        attempts = (self.retries + 1) if op in _IDEMPOTENT else 1
        delay = self.backoff
        last = None
        with self._lock:
            for attempt in range(attempts):
                if attempt:
                    time.sleep(delay * (1.0 + 0.25 * random.random()))
                    delay = min(self.backoff_max, delay * 2.0)
                try:
                    self._ensure_session()
                    return self._roundtrip(op, args, timeout)
                except (StaleEpochError, TimeoutError):
                    raise       # typed/terminal — not a transport loss
                except (OSError, EOFError, pickle.UnpicklingError,
                        struct.error) as e:
                    last = e
                    self._drop_session()
                    if self._outage_t0 is None:
                        self._outage_t0 = time.monotonic()
                    self._m_outage.set(
                        time.monotonic() - self._outage_t0)
            raise StoreUnavailableError(self.addr, op,
                                        detail=repr(last)) from last

    # -- KV surface (native TCPStore parity) ----------------------------
    def _kv_call(self, op, *args, timeout=None):
        """KV ops prefer the server's advertised native offload (the
        C++ fast path) when one exists; transport failures there drop
        the whole session and fall back through the retry envelope."""
        with self._lock:
            native = self._native
        if native is None:
            return self._call(op, *args, timeout=timeout)
        if _om.enabled():
            self._m_ops.labels(op).inc()
        try:
            if op == "set":
                native.set(args[0], args[1])
                return True
            if op == "get":
                return native.get(args[0], timeout=timeout)
            if op == "add":
                return native.add(args[0], args[1])
            if op == "del":
                return native.delete_key(args[0])
            if op == "wait":
                native.wait(args[0], timeout=timeout)
                return True
            if op == "numkeys":
                return native.num_keys()
        except TimeoutError:
            raise
        except (OSError, RuntimeError) as e:
            with self._lock:
                self._drop_session()
            raise StoreUnavailableError(self.addr, op,
                                        detail=repr(e)) from e
        raise ValueError(f"not a KV op: {op!r}")

    def set(self, key, value):
        if isinstance(value, str):
            value = value.encode()
        self._kv_call("set", key, bytes(value))

    def get(self, key, timeout=None):
        return self._kv_call(
            "get", key, self.timeout if timeout is None else timeout,
            timeout=self.timeout if timeout is None else timeout)

    def add(self, key, delta=1):
        return self._kv_call("add", key, int(delta))

    def delete_key(self, key):
        return self._kv_call("del", key)

    def wait(self, keys, timeout=None):
        t = self.timeout if timeout is None else timeout
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            self._kv_call("wait", k, t, timeout=t)

    def num_keys(self):
        return self._kv_call("numkeys")

    def barrier(self, world_size, tag="barrier", timeout=None):
        arrived = self.add(f"_{tag}/count", 1)
        if arrived == world_size:
            self.set(f"_{tag}/done", b"1")
        self.wait(f"_{tag}/done", timeout)

    # -- membership surface (FileStore parity) --------------------------
    def register(self, host_id, epoch=None):
        self._call("register", str(host_id),
                   None if epoch is None else int(epoch))

    def heartbeat(self, host_id, epoch=None):
        """Refresh a live host's lease. Same chaos surface as
        FileStore: the ``store.heartbeat`` NETWORK point fires first
        (drop -> the beat is silently lost, returns False;
        delay/hold -> in-flight latency), so PR 11 partition plans
        drive either backend unchanged."""
        verdict = _faults.fire_network("store.heartbeat",
                                       src=str(host_id), dst="store")
        if verdict.delay or verdict.hold:
            time.sleep(verdict.delay + verdict.hold)
        if verdict.drop:
            return False
        self._call("heartbeat", str(host_id),
                   None if epoch is None else int(epoch))
        return True

    def heartbeat_age(self, host_id):
        return self._call("hb_age", str(host_id))

    def deregister(self, host_id):
        self._call("dereg", str(host_id))

    def hosts(self):
        return self._call("hosts", self.ttl)

    def next_epoch(self, host_id, timeout=5.0):
        return self._call("next_epoch", str(host_id))

    def epoch_of(self, host_id):
        return self._call("epoch_of", str(host_id))

    def check_epoch(self, host_id, epoch):
        """Client-side convenience probe of the server's fence (the
        authoritative check runs server-side on every fenced op)."""
        if epoch is None:
            return
        current = self.epoch_of(host_id)
        if current is not None and int(epoch) < current:
            self._m_stale.inc()
            raise StaleEpochError(str(host_id), int(epoch), current)

    def ping(self, timeout=None):
        """One round trip; raises :class:`StoreUnavailableError` when
        the server is unreachable."""
        return self._call("ping", timeout=timeout)

    def close(self):
        with self._lock:
            self._drop_session()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------
# standalone server process (the chaos tests' SIGKILL target)
# ---------------------------------------------------------------------
def _main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="run a standalone LeaseStoreServer")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--native-kv", action="store_true",
                    help="front the C++ TCPStore for KV ops when the "
                         "native build is available")
    args = ap.parse_args(argv)
    srv = LeaseStoreServer(port=args.port, host=args.host,
                           native_kv=args.native_kv)
    print(f"lease-store listening on {srv.host}:{srv.port}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
