"""Reverse-mode autograd engine.

Analog of the reference's queue-based backward runner
(`paddle/fluid/eager/backward.cc` — ``RunBackward`` + ``GeneralGrad`` for
``paddle.grad()``). Works on the GradNode tape recorded by
``framework.tensor.run_op``; each node's backward is a ``jax.vjp`` closure, so
gradients are exactly JAX's gradients.
"""

from __future__ import annotations

import jax.numpy as jnp

from .tensor import Tensor, GradNode

__all__ = ["backward", "grad"]


def _topo_order(roots):
    """Reverse-topological order of GradNodes reachable from root tensors."""
    visited = set()
    order = []

    def visit(node):
        if node is None or id(node) in visited:
            return
        visited.add(id(node))
        for t in node.inputs:
            visit(t._node)
        order.append(node)

    for t in roots:
        visit(t._node)
    order.reverse()
    return order


def _run(tensors, grad_tensors, accumulate_into_grad, target_ids=None,
         retain_graph=False, create_graph=False):
    """Core engine shared by ``Tensor.backward`` and ``paddle.grad``.

    grads are accumulated per *Tensor object* (keyed by id), matching the
    reference's ``GradTensorHolder`` multi-path accumulation.
    """
    from .tensor import no_grad

    # cotangent store: id(tensor) -> jnp array
    cotangents = {}
    holders = {}  # id -> Tensor (keep alive)

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._node is None:
            raise RuntimeError(
                "backward() called on a tensor with stop_gradient=True and no "
                "grad history")
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad_tensor must be given for non-scalar outputs "
                    f"(shape {t.shape})")
            g_arr = jnp.ones_like(t._data)
        else:
            g_arr = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        cotangents[id(t)] = cotangents.get(id(t), 0) + g_arr
        holders[id(t)] = t

    order = _topo_order(tensors)

    # map (node, out_index) -> output tensor ids seen on the tape: we stored
    # the linkage on the tensors themselves, so walk tensors via node inputs.
    # Output tensors are only reachable as graph roots or as node inputs, and
    # each records (_node, _out_index); collect them lazily as we traverse.
    def fire_hooks(t, g_arr):
        if t._backward_hooks:
            tg = Tensor(g_arr, stop_gradient=not create_graph)
            for hook in t._backward_hooks:
                r = hook(tg)
                if r is not None:
                    tg = r if isinstance(r, Tensor) else Tensor(r)
            return tg._data
        return g_arr

    grad_ctx = (lambda: _null_ctx()) if create_graph else no_grad

    results = {}
    with grad_ctx():
        for node in order:
            # gather cotangents for this node's outputs
            outs = []
            any_ct = False
            for i in range(node.n_outputs):
                found = None
                for tid, arr in cotangents.items():
                    t = holders[tid]
                    if t._node is node and t._out_index == i:
                        found = arr
                        break
                if found is None:
                    shape, dt = node.out_avals[i]
                    outs.append(jnp.zeros(shape, dt))
                else:
                    any_ct = True
                    outs.append(found)
            if not any_ct:
                continue
            ct_in = node.vjp_fn(tuple(outs) if node.n_outputs > 1 else outs[0])
            for t, g_arr in zip(node.inputs, ct_in):
                g_arr = fire_hooks(t, g_arr)
                key = id(t)
                holders[key] = t
                if key in cotangents:
                    cotangents[key] = cotangents[key] + g_arr
                else:
                    cotangents[key] = g_arr
            if not retain_graph:
                node.vjp_fn = _used_up

    # write leaf grads
    for tid, arr in cotangents.items():
        t = holders[tid]
        if target_ids is not None:
            if tid in target_ids:
                results[tid] = arr
            continue
        if t._node is None and not t.stop_gradient:
            if accumulate_into_grad:
                if t.grad is None:
                    t.grad = Tensor(arr, stop_gradient=True)
                else:
                    t.grad = Tensor(t.grad._data + arr, stop_gradient=True)
    return results


def _used_up(*_):
    raise RuntimeError(
        "Trying to backward through the graph a second time. Set "
        "retain_graph=True when calling backward the first time.")


class _null_ctx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


def backward(tensors, grad_tensors=None, retain_graph=False):
    """``paddle.autograd.backward`` — accumulate into ``.grad`` of leaves."""
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    _run(tensors, grad_tensors, accumulate_into_grad=True,
         retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """``paddle.grad`` — return grads of ``inputs`` without touching ``.grad``.

    Reference: ``GeneralGrad`` in `fluid/eager/backward.cc:103`.
    """
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph
    target_ids = {id(t) for t in inputs}
    res = _run(outputs, grad_outputs, accumulate_into_grad=False,
               target_ids=target_ids, retain_graph=retain_graph,
               create_graph=create_graph)
    out = []
    for t in inputs:
        if id(t) in res:
            out.append(Tensor(res[id(t)], stop_gradient=not create_graph))
        else:
            if not allow_unused:
                raise RuntimeError(
                    "One of the input tensors was not used in the graph "
                    "(pass allow_unused=True to return None for it).")
            out.append(None)
    return out
