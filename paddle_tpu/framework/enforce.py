"""Typed error layer — the reference's PADDLE_ENFORCE discipline.

Reference: `paddle/common/enforce.h` (PADDLE_ENFORCE_* macros raising
typed EnforceNotMet errors with operator context) and
`paddle/phi/core/errors.h` (the error-code taxonomy). Python analog:
typed exception classes + ``enforce``/``check_type``/``check_dtype``
helpers, and operator context attached to any exception crossing the
eager dispatch seam (``run_op`` adds a PEP-678 note naming the op), so
failures read as framework errors, not raw JAX tracebacks.
"""

from __future__ import annotations

__all__ = ["EnforceNotMet", "InvalidArgumentError", "NotFoundError",
           "OutOfRangeError", "AlreadyExistsError", "PermissionDeniedError",
           "UnimplementedError", "UnavailableError",
           "PreconditionNotMetError", "enforce", "check_type",
           "check_dtype", "attach_op_context"]


class EnforceNotMet(RuntimeError):
    """Base of all framework-raised errors (reference enforce.h:EnforceNotMet)."""


class InvalidArgumentError(EnforceNotMet, ValueError):
    pass


class NotFoundError(EnforceNotMet, KeyError):
    pass


class OutOfRangeError(EnforceNotMet, IndexError):
    pass


class AlreadyExistsError(EnforceNotMet):
    pass


class PermissionDeniedError(EnforceNotMet):
    pass


class UnimplementedError(EnforceNotMet, NotImplementedError):
    pass


class UnavailableError(EnforceNotMet):
    pass


class PreconditionNotMetError(EnforceNotMet):
    pass


def enforce(condition, message, *args, exc=InvalidArgumentError):
    """PADDLE_ENFORCE: raise ``exc`` with a formatted message unless
    ``condition`` holds."""
    if not condition:
        raise exc(message.format(*args) if args else message)


def check_type(value, name, expected_type, op_name):
    """Reference: `python/paddle/base/data_feeder.py` check_type."""
    if not isinstance(value, expected_type):
        names = getattr(expected_type, "__name__", None) or ", ".join(
            t.__name__ for t in expected_type)
        raise InvalidArgumentError(
            f"The type of '{name}' in {op_name} must be {names}, "
            f"but received {type(value).__name__}.")


def check_dtype(dtype, name, expected_dtypes, op_name):
    """Reference: data_feeder.py check_dtype."""
    d = str(dtype).replace("paddle.", "")
    expected = [str(e) for e in expected_dtypes]
    if d not in expected and d.split(".")[-1] not in expected:
        raise InvalidArgumentError(
            f"The dtype of '{name}' in {op_name} must be one of "
            f"{expected}, but received {d}.")


def attach_op_context(exc, op_name):
    """Tag an in-flight exception with the operator it crossed (PEP 678
    note — the analog of enforce.h's operator-context frames). On
    Python < 3.11, where ``add_note`` doesn't exist, the ``__notes__``
    list is maintained by hand — same attribute, same traceback
    rendering under 3.11+ semantics."""
    note = f"[operator '{op_name}' of paddle_tpu]"
    try:
        if hasattr(exc, "add_note"):
            exc.add_note(note)
        else:
            notes = getattr(exc, "__notes__", None)
            if not isinstance(notes, list):
                notes = []
                exc.__notes__ = notes
            notes.append(note)
    except (TypeError, AttributeError):
        pass
    return exc
