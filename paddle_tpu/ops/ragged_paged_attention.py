"""Ragged paged attention: ONE kernel for a mixed prefill+decode batch.

Capability reference: *Ragged Paged Attention* (arXiv 2604.15464) — a
single TPU kernel that consumes a batch of variable-length prefill
chunks AND single-token decode rows over a shared paged KV pool, so a
serving scheduler never has to serialize the two phases into separate
dispatches. This is the kernel behind the chunked-prefill serving
engine (`paddle_tpu/inference/serving.py`): every engine step is one
dispatch of this kernel over rows described by per-row
``(query_len, kv_len)`` metadata, whether the row is a 128-token
prompt chunk or one decode token.

Shapes (R rows, each a prefill chunk or a decode step of one sequence):
  q             [R, QB, H, D]       per-row query block; rows are padded
                                    to the static block QB — entries at
                                    qi >= q_lens[r] are padding and come
                                    back as zeros
  k_pages       [P, Hk, page, D]    global pool, head-major (same layout
                                    as `paged_attention`)
  v_pages       [P, Hk, page, D]
  k_scale       [P, Hk, page, 1]    OPTIONAL f32 dequant sidecars for
  v_scale       [P, Hk, page, 1]    int8 pools: per-head per-slot
                                    symmetric scales written by
                                    `quantize_kv_int8` — the kernel's
                                    kv loop dequantizes
                                    ``int8 * scale`` in f32 before the
                                    softmax, so int8 pages halve (bf16)
                                    or quarter (f32) HBM page bytes
                                    with no change to the attention
                                    math's accumulation order
  block_tables  [R, W] int32        page ids per ROW's sequence (tail
                                    entries clamped into [0, P))
  kv_lens       [R] int32           total context of the row's sequence
                                    *including* this row's query tokens
                                    (0 marks an inactive row — output 0)
  q_starts      [R] int32           absolute position of the row's first
                                    query token in its sequence
  q_lens        [R] int32           valid query tokens in the row
                                    (1 for decode rows, up to QB for
                                    prefill chunks)
  -> out        [R, QB, H, D]

Semantics: query token qi of row r sits at absolute position
``p = q_starts[r] + qi`` and attends kv positions ``[0, p]`` (causal)
clipped to ``[0, kv_lens[r])``. A decode row (q_len 1,
q_start = kv_len - 1) reduces EXACTLY to `paged_attention`'s math — the
same online-softmax update in the same order — so decode tokens are
bitwise-identical to the decode-only kernel. Two chunks of the same
sequence may appear as two rows of one batch (same block table,
consecutive q_starts): their K/V must already be in the pool, which the
serving engine guarantees by scattering every row's K/V before the
attention of any row.

The kernel runs grid (R, Hk, W) with one online-softmax accumulator in
VMEM scratch per (row, kv-head); the prefetched block table picks which
HBM page each grid step streams into VMEM, and pages at or past
``kv_lens[r]`` are skipped. Inference-only: no VJP.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..framework.tensor import run_op

__all__ = ["ragged_paged_attention", "ragged_paged_attention_xla",
           "supported"]

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def supported(q, k_pages, v_pages, block_tables, kv_lens, q_starts,
              q_lens, k_scale=None, v_scale=None):
    if not _HAS_PLTPU:
        return False
    if (k_scale is None) != (v_scale is None):
        return False
    if k_scale is not None:
        ks = getattr(k_scale, "_data", k_scale)
        vs = getattr(v_scale, "_data", v_scale)
        want = tuple(getattr(k_pages, "_data", k_pages).shape[:3]) + (1,)
        if tuple(ks.shape) != want or tuple(vs.shape) != want:
            return False
    qs = getattr(q, "_data", q).shape
    ks = getattr(k_pages, "_data", k_pages).shape
    bt = getattr(block_tables, "_data", block_tables).shape
    shapes1 = [getattr(a, "_data", a).shape
               for a in (kv_lens, q_starts, q_lens)]
    if len(qs) != 4 or len(ks) != 4 or len(bt) != 2 \
            or any(len(s) != 1 for s in shapes1):
        return False
    r, qb, h, d = qs
    p, hk, page_size, dk = ks
    if getattr(v_pages, "_data", v_pages).shape != tuple(ks):
        return False
    if d != dk or hk == 0 or h % hk or bt[0] != r:
        return False
    if any(s[0] != r for s in shapes1):
        return False
    if d % 8 or d > 256 or page_size % 8 or qb < 1:
        return False
    return True


def _ragged_kernel(tables_ref, kv_lens_ref, q_starts_ref, q_lens_ref,
                   q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, page_size, group, scale):
    r = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = kv_lens_ref[r]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [QB*G, D]
        k = k_ref[0, 0].astype(jnp.float32)              # [page, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        kpos = page_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        # query rows are laid out [QB, G] flattened (qi major): the
        # token index of softmax row i is i // G
        qrow = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group
        qpos = q_starts_ref[r] + qrow
        valid = (kpos <= qpos) & (kpos < ctx) & (qrow < q_lens_ref[r])
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        # fully-masked softmax rows (a padded query, or a page entirely
        # behind this query's causal horizon) must contribute nothing:
        # with finite NEG_INF, exp(s - m_new) would be exp(0) = 1 when
        # m_new is still NEG_INF, silently polluting l and acc
        pexp = jnp.where(valid, pexp, 0.0)
        l_ref[...] = l_prev * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(p == num_pages - 1)
    def _finish():
        l = l_ref[...]
        # l == 0: inactive row (kv_len 0) or padded query row — emit
        # zeros, never NaN
        out = acc_ref[...] / jnp.where(l > 0.0, l, 1.0)
        o_ref[0, 0] = jnp.where(l > 0.0, out, 0.0).astype(o_ref.dtype)


def _ragged_kernel_q8(tables_ref, kv_lens_ref, q_starts_ref, q_lens_ref,
                      q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                      acc_ref, m_ref, l_ref, *, page_size, group, scale):
    """Int8-pool variant: identical online-softmax math to
    `_ragged_kernel`, with the streamed K/V page dequantized in f32
    (``int8 * per-slot scale``) before the dot products. Kept separate
    so the float path's decode-bitwise contract stays untouched."""
    r = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = kv_lens_ref[r]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [QB*G, D]
        # dequantize the page in VMEM: [page, D] int8 * [page, 1] f32
        k = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0]
        v = v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        kpos = page_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        qrow = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group
        qpos = q_starts_ref[r] + qrow
        valid = (kpos <= qpos) & (kpos < ctx) & (qrow < q_lens_ref[r])
        s = jnp.where(valid, s, NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        pexp = jnp.where(valid, pexp, 0.0)
        l_ref[...] = l_prev * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(p == num_pages - 1)
    def _finish():
        l = l_ref[...]
        out = acc_ref[...] / jnp.where(l > 0.0, l, 1.0)
        o_ref[0, 0] = jnp.where(l > 0.0, out, 0.0).astype(o_ref.dtype)


@functools.lru_cache(maxsize=32)
def _make_ragged_q8(scale, page_size, qb, group, interpret):
    def call(q4, k_pages, v_pages, k_scale, v_scale, tables, kv_lens,
             q_starts, q_lens):
        r, hk, qbg, d = q4.shape
        max_pages = tables.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(r, hk, max_pages),
            in_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                # the scale sidecars stream with their page
                pl.BlockSpec((1, 1, page_size, 1),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, 1),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, qbg, d),
                lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((qbg, d), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(_ragged_kernel_q8, page_size=page_size,
                              group=group, scale=scale),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((r, hk, qbg, d), q4.dtype),
            interpret=interpret,
        )(tables, kv_lens, q_starts, q_lens, q4, k_pages, v_pages,
          k_scale, v_scale)

    return call


@functools.lru_cache(maxsize=32)
def _make_ragged(scale, page_size, qb, group, interpret):
    def call(q4, k_pages, v_pages, tables, kv_lens, q_starts, q_lens):
        r, hk, qbg, d = q4.shape
        max_pages = tables.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(r, hk, max_pages),
            in_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                # the prefetched block table picks the HBM page to stream
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, qbg, d),
                lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((qbg, d), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(_ragged_kernel, page_size=page_size,
                              group=group, scale=scale),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((r, hk, qbg, d), q4.dtype),
            interpret=interpret,
        )(tables, kv_lens, q_starts, q_lens, q4, k_pages, v_pages)

    return call


def _ragged_impl_q8(q, k_pages, v_pages, k_scale, v_scale, block_tables,
                    kv_lens, q_starts, q_lens, scale):
    r, qb, h, d = q.shape
    hk = k_pages.shape[1]
    group = h // hk
    page_size = k_pages.shape[2]
    q4 = q.reshape(r, qb, hk, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, hk, qb * group, d)
    call = _make_ragged_q8(scale, page_size, qb, group, _interpret())
    tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                      k_pages.shape[0] - 1)
    out = call(q4, k_pages, v_pages, k_scale.astype(jnp.float32),
               v_scale.astype(jnp.float32), tables,
               kv_lens.astype(jnp.int32), q_starts.astype(jnp.int32),
               q_lens.astype(jnp.int32))
    return out.reshape(r, hk, qb, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, qb, h, d)


def _ragged_impl(q, k_pages, v_pages, block_tables, kv_lens, q_starts,
                 q_lens, scale):
    r, qb, h, d = q.shape
    hk = k_pages.shape[1]
    group = h // hk
    page_size = k_pages.shape[2]
    # [R, QB, Hk, G, D] -> [R, Hk, QB*G, D]: one MXU operand per
    # (row, kv-head) with the GQA group riding inside the query block
    q4 = q.reshape(r, qb, hk, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, hk, qb * group, d)
    call = _make_ragged(scale, page_size, qb, group, _interpret())
    # clamp table tails (see paged_attention): they feed the index map
    tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                      k_pages.shape[0] - 1)
    out = call(q4, k_pages, v_pages, tables, kv_lens.astype(jnp.int32),
               q_starts.astype(jnp.int32), q_lens.astype(jnp.int32))
    return out.reshape(r, hk, qb, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, qb, h, d)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, kv_lens,
                           q_starts, q_lens, scale=None, k_scale=None,
                           v_scale=None):
    """Mixed prefill+decode attention over the paged pool (see module
    docstring). Tape-integrated but non-differentiable (serving path).
    Pass ``k_scale``/``v_scale`` sidecars ([P, Hk, page, 1] f32) with
    int8 pools — the kernel dequantizes inside its kv loop."""
    if not supported(q, k_pages, v_pages, block_tables, kv_lens,
                     q_starts, q_lens, k_scale, v_scale):
        raise ValueError(
            "ragged_paged_attention preconditions not met: need q "
            "[R,QB,H,D], pages [P,Hk,page,D] (page % 8 == 0, D % 8 == 0, "
            "D <= 256, H % Hk == 0), tables [R,max_pages], kv_lens/"
            "q_starts/q_lens [R]; int8 pools need BOTH k_scale/v_scale "
            "sidecars shaped [P,Hk,page,1]")
    d = getattr(q, "_data", q).shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    if k_scale is not None:
        def fn_q8(q, kp, vp, ks, vs, bt, kl, qs, ql):
            return _ragged_impl_q8(q, kp, vp, ks, vs, bt, kl, qs, ql, s)

        return run_op("ragged_paged_attention_q8", fn_q8,
                      (q, k_pages, v_pages, k_scale, v_scale,
                       block_tables, kv_lens, q_starts, q_lens),
                      differentiable=False)

    def fn(q, kp, vp, bt, kl, qs, ql):
        return _ragged_impl(q, kp, vp, bt, kl, qs, ql, s)

    return run_op("ragged_paged_attention", fn,
                  (q, k_pages, v_pages, block_tables, kv_lens, q_starts,
                   q_lens), differentiable=False)


def ragged_paged_attention_xla(q, k_pages, v_pages, block_tables,
                               kv_lens, q_starts, q_lens, scale=None,
                               k_scale=None, v_scale=None):
    """XLA reference path: gather every row's pages to a contiguous
    [R, S, Hk, D] window, apply the causal/ragged mask, softmax.
    Semantically identical to the kernel (zeros on padded query rows
    and inactive rows; int8 pools dequantized by the scale sidecars);
    used for parity tests and as the fallback where Pallas is
    unavailable."""
    q, k_pages, v_pages, block_tables, kv_lens, q_starts, q_lens = (
        getattr(a, "_data", a)
        for a in (q, k_pages, v_pages, block_tables, kv_lens, q_starts,
                  q_lens))
    r, qb, h, d = q.shape
    p, hk, page_size, _ = k_pages.shape
    group = h // hk
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    tables = jnp.clip(block_tables.astype(jnp.int32), 0, p - 1)
    if k_scale is not None:
        ks = getattr(k_scale, "_data", k_scale).astype(jnp.float32)
        vs = getattr(v_scale, "_data", v_scale).astype(jnp.float32)
        k_pages = k_pages.astype(jnp.float32) * ks
        v_pages = v_pages.astype(jnp.float32) * vs
    # [R, W, Hk, page, D] -> [R, S, Hk, D]
    k = jnp.swapaxes(k_pages[tables], 2, 3).reshape(r, -1, hk, d)
    v = jnp.swapaxes(v_pages[tables], 2, 3).reshape(r, -1, hk, d)
    kq = jnp.repeat(k, group, axis=2)
    vq = jnp.repeat(v, group, axis=2)
    logits = jnp.einsum("rqhd,rshd->rhqs", q.astype(jnp.float32),
                        kq.astype(jnp.float32)) * s
    S = k.shape[1]
    kpos = jnp.arange(S)[None, None, None, :]
    qpos = (q_starts[:, None] + jnp.arange(qb)[None, :])[:, None, :, None]
    qvalid = (jnp.arange(qb)[None, :]
              < q_lens[:, None])[:, None, :, None]
    mask = (kpos <= qpos) & (kpos < kv_lens[:, None, None, None]) & qvalid
    logits = jnp.where(mask, logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    # fully-masked rows (padding / inactive) -> zeros, matching the
    # kernel's l == 0 guard rather than softmax's uniform fallback
    any_valid = jnp.any(mask, axis=-1, keepdims=True)
    w = jnp.where(any_valid, w, 0.0)
    out = jnp.einsum("rhqs,rshd->rqhd", w, vq.astype(jnp.float32))
    return out.astype(q.dtype)
