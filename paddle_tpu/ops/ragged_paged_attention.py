"""Ragged paged attention: ONE kernel for a mixed prefill+decode batch.

Capability reference: *Ragged Paged Attention* (arXiv 2604.15464) — a
single TPU kernel that consumes a batch of variable-length prefill
chunks AND single-token decode rows over a shared paged KV pool, so a
serving scheduler never has to serialize the two phases into separate
dispatches. This is the kernel behind the chunked-prefill serving
engine (`paddle_tpu/inference/serving.py`): every engine step is one
dispatch of this kernel over rows described by per-row
``(query_len, kv_len)`` metadata, whether the row is a 128-token
prompt chunk or one decode token.

Shapes (R rows, each a prefill chunk or a decode step of one sequence):
  q             [R, QB, H, D]       per-row query block; rows are padded
                                    to the static block QB — entries at
                                    qi >= q_lens[r] are padding and come
                                    back as zeros
  k_pages       [P, Hk, page, D]    global pool, head-major (same layout
                                    as `paged_attention`)
  v_pages       [P, Hk, page, D]
  k_scale       [P, Hk, page, 1]    OPTIONAL f32 dequant sidecars for
  v_scale       [P, Hk, page, 1]    int8 pools: per-head per-slot
                                    symmetric scales written by
                                    `quantize_kv_int8` — the kernel's
                                    kv loop dequantizes
                                    ``int8 * scale`` in f32 before the
                                    softmax, so int8 pages halve (bf16)
                                    or quarter (f32) HBM page bytes
                                    with no change to the attention
                                    math's accumulation order
  block_tables  [R, W] int32        page ids per ROW's sequence (tail
                                    entries clamped into [0, P))
  kv_lens       [R] int32           total context of the row's sequence
                                    *including* this row's query tokens
                                    (0 marks an inactive row — output 0)
  q_starts      [R] int32           absolute position of the row's first
                                    query token in its sequence
  q_lens        [R] int32           valid query tokens in the row
                                    (1 for decode rows, up to QB for
                                    prefill chunks)
  -> out        [R, QB, H, D]

Semantics: query token qi of row r sits at absolute position
``p = q_starts[r] + qi`` and attends kv positions ``[0, p]`` (causal)
clipped to ``[0, kv_lens[r])``. A decode row (q_len 1,
q_start = kv_len - 1) reduces EXACTLY to `paged_attention`'s math — the
same online-softmax update in the same order — so decode tokens are
bitwise-identical to the decode-only kernel. Two chunks of the same
sequence may appear as two rows of one batch (same block table,
consecutive q_starts): their K/V must already be in the pool, which the
serving engine guarantees by scattering every row's K/V before the
attention of any row.

The kernel runs grid (R, Hk, W) with one online-softmax accumulator in
VMEM scratch per (row, kv-head); the prefetched block table picks which
HBM page each grid step streams into VMEM, and pages at or past
``kv_lens[r]`` are skipped. Inference-only: no VJP.

Fused KV write (`fused_ragged_paged_attention`): the first step toward
the per-layer decode megakernel (ROADMAP item 2; MPK arXiv 2512.22219,
Neptune arXiv 2510.08726). The serving engine's unfused step scatters
the current tokens' post-rope K/V into the pools with a separate XLA
op, then this kernel re-reads the same pages through the same block
tables — an HBM round trip per layer at exactly the producer/consumer
locality boundary both papers name. The fused variant takes the packed
new K/V rows (``new_k/new_v [T, Hk, D]``, the flat token axis of the
mixed dispatch) plus per-row write metadata and performs the page write
INSIDE the Pallas program, returning the updated pools through
aliased outputs (`input_output_aliases`), so the scatter op — and its
round trip — disappears.

Ordering contract (the subtlety): later prefill chunks of one prompt
may sit in the SAME grid as the rows that produce the K/V they must
attend. The kernel does not rely on in-kernel write-then-read
visibility at all — pipelined page fetches may legally race in-kernel
writes. Instead every row REPLAYS the dispatch's writes on read:
positions ``[w_start[r], kv_lens[r])`` of row r's sequence were
written by rows <= r of this dispatch and are overlaid from the packed
``new_k/new_v`` rows (their flat indices are affine in the position:
chunks of one sequence are packed contiguously in position order, so
position p lives at flat index ``w_flat[r] + p - w_start[r]``); only
positions below ``w_start[r]`` come from the streamed page. The HBM
write-back itself is done ONCE per page, by the sequence's LAST row in
the dispatch (``kv_lens[r] == w_end[r]``) — no page is the write
target of two grid steps, so no copy-out ordering between steps is
ever required. Grid steps whose page holds no new token write to the
caller-designated ``dump_page`` (the serving engine's trash page).
The q8 path quantizes the fresh rows in-kernel with bitwise the same
math as ``quantize_kv_int8`` (per-head-per-slot symmetric absmax
scales into the ``[P, Hk, page, 1]`` sidecars), so fused and unfused
pools agree bit for bit.

`ragged_paged_attention_xla` stays a WRITE-THEN-READ exact-parity
reference on purpose: two dependent XLA ops have unambiguous
sequential semantics, which is what the fused kernel's replay must be
proven against (`fused_ragged_paged_attention_xla` composes them).

Fused rotary embedding (ROADMAP item 2, second stage): passing
``rope_sin``/``rope_cos`` — per-dispatch ``[T, D]`` f32 tables, one row
per PACKED token (``sin(pos * inv_freq)`` with the neox duplicated-half
layout, computed ONCE per dispatch and shared by every layer) — makes
the fused kernel consume PRE-rope operands: ``q`` arrives in the packed
token layout ``[T, H, D]`` (no host-side row-block gather; each row's
query tokens sit contiguously on the packed axis at
``w_flat[r] + q_start[r] - w_start[r]``, the same affine replay index
the KV overlay already uses, so the kernel slices them with the
scalar-prefetched metadata) and ``new_k`` is the pre-rope packed K.
The kernel applies the rotation in VMEM — ``x * cos +
rotate_half(x) * sin`` in f32, cast back to the model dtype — before
the write/attention math, with bitwise the same value chain as the
unfused ``fused_rotary_position_embedding`` + scatter pipeline: the
transcendentals live in the XLA-computed tables, so the kernel adds
only IEEE-exact multiplies/adds and greedy outputs and pool bytes stay
bitwise across all three paths (rope-fused / PR-13 fused-KV /
two-op). ``qblock`` (the row-block width the caller's metadata was
built for) becomes an explicit argument because packed q no longer
carries it. This deletes the per-layer rope elementwise op (2 HBM
round trips per layer: q and k) and the per-layer q gather from the
mixed program.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..framework.tensor import run_op

__all__ = ["ragged_paged_attention", "ragged_paged_attention_xla",
           "supported", "fused_ragged_paged_attention",
           "fused_ragged_paged_attention_xla", "fused_supported",
           "fused_rope_geometry_ok", "rope_tables"]

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def supported(q, k_pages, v_pages, block_tables, kv_lens, q_starts,
              q_lens, k_scale=None, v_scale=None):
    if not _HAS_PLTPU:
        return False
    if (k_scale is None) != (v_scale is None):
        return False
    if k_scale is not None:
        ks = getattr(k_scale, "_data", k_scale)
        vs = getattr(v_scale, "_data", v_scale)
        want = tuple(getattr(k_pages, "_data", k_pages).shape[:3]) + (1,)
        if tuple(ks.shape) != want or tuple(vs.shape) != want:
            return False
    qs = getattr(q, "_data", q).shape
    ks = getattr(k_pages, "_data", k_pages).shape
    bt = getattr(block_tables, "_data", block_tables).shape
    shapes1 = [getattr(a, "_data", a).shape
               for a in (kv_lens, q_starts, q_lens)]
    if len(qs) != 4 or len(ks) != 4 or len(bt) != 2 \
            or any(len(s) != 1 for s in shapes1):
        return False
    r, qb, h, d = qs
    p, hk, page_size, dk = ks
    if getattr(v_pages, "_data", v_pages).shape != tuple(ks):
        return False
    if d != dk or hk == 0 or h % hk or bt[0] != r:
        return False
    if any(s[0] != r for s in shapes1):
        return False
    if d % 8 or d > 256 or page_size % 8 or qb < 1:
        return False
    return True


def _softmax_accumulate(q, k, v, page_start, q_start, q_len, ctx,
                        group, acc_ref, m_ref, l_ref):
    """ONE page step of the shared online-softmax update: causal/
    ragged masking, running max/sum rescale, accumulator update. Every
    kernel in this module calls exactly this body — the engine's
    cross-path bitwise parity contract requires the accumulation math
    to be maintained in ONE place, never per-kernel copies. ``q``
    ``[QB*G, D]`` is pre-scaled f32; ``k``/``v`` ``[page, D]`` f32."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    kpos = page_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    # query rows are laid out [QB, G] flattened (qi major): the
    # token index of softmax row i is i // G
    qrow = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group
    qpos = q_start + qrow
    valid = (kpos <= qpos) & (kpos < ctx) & (qrow < q_len)
    s = jnp.where(valid, s, NEG_INF)
    m_prev, l_prev = m_ref[...], l_ref[...]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    pexp = jnp.exp(s - m_new)
    # fully-masked softmax rows (a padded query, or a page entirely
    # behind this query's causal horizon) must contribute nothing:
    # with finite NEG_INF, exp(s - m_new) would be exp(0) = 1 when
    # m_new is still NEG_INF, silently polluting l and acc
    pexp = jnp.where(valid, pexp, 0.0)
    l_ref[...] = l_prev * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    m_ref[...] = m_new
    acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
        pexp, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _softmax_finish(o_ref, acc_ref, l_ref):
    """Emit the normalized accumulator on the last page step. l == 0:
    inactive row (kv_len 0) or padded query row — emit zeros, never
    NaN."""
    l = l_ref[...]
    out = acc_ref[...] / jnp.where(l > 0.0, l, 1.0)
    o_ref[0, 0] = jnp.where(l > 0.0, out, 0.0).astype(o_ref.dtype)


def _ragged_kernel(tables_ref, kv_lens_ref, q_starts_ref, q_lens_ref,
                   q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, page_size, group, scale):
    r = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = kv_lens_ref[r]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [QB*G, D]
        k = k_ref[0, 0].astype(jnp.float32)              # [page, D]
        v = v_ref[0, 0].astype(jnp.float32)
        _softmax_accumulate(q, k, v, page_start, q_starts_ref[r],
                            q_lens_ref[r], ctx, group, acc_ref, m_ref,
                            l_ref)

    @pl.when(p == num_pages - 1)
    def _finish():
        _softmax_finish(o_ref, acc_ref, l_ref)


def _ragged_kernel_q8(tables_ref, kv_lens_ref, q_starts_ref, q_lens_ref,
                      q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                      acc_ref, m_ref, l_ref, *, page_size, group, scale):
    """Int8-pool variant: identical online-softmax math to
    `_ragged_kernel`, with the streamed K/V page dequantized in f32
    (``int8 * per-slot scale``) before the dot products. Kept separate
    so the float path's decode-bitwise contract stays untouched."""
    r = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = kv_lens_ref[r]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [QB*G, D]
        # dequantize the page in VMEM: [page, D] int8 * [page, 1] f32
        k = k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0]
        v = v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0]
        _softmax_accumulate(q, k, v, page_start, q_starts_ref[r],
                            q_lens_ref[r], ctx, group, acc_ref, m_ref,
                            l_ref)

    @pl.when(p == num_pages - 1)
    def _finish():
        _softmax_finish(o_ref, acc_ref, l_ref)


@functools.lru_cache(maxsize=32)
def _make_ragged_q8(scale, page_size, qb, group, interpret):
    def call(q4, k_pages, v_pages, k_scale, v_scale, tables, kv_lens,
             q_starts, q_lens):
        r, hk, qbg, d = q4.shape
        max_pages = tables.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(r, hk, max_pages),
            in_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                # the scale sidecars stream with their page
                pl.BlockSpec((1, 1, page_size, 1),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, 1),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, qbg, d),
                lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((qbg, d), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(_ragged_kernel_q8, page_size=page_size,
                              group=group, scale=scale),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((r, hk, qbg, d), q4.dtype),
            interpret=interpret,
        )(tables, kv_lens, q_starts, q_lens, q4, k_pages, v_pages,
          k_scale, v_scale)

    return call


@functools.lru_cache(maxsize=32)
def _make_ragged(scale, page_size, qb, group, interpret):
    def call(q4, k_pages, v_pages, tables, kv_lens, q_starts, q_lens):
        r, hk, qbg, d = q4.shape
        max_pages = tables.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(r, hk, max_pages),
            in_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                # the prefetched block table picks the HBM page to stream
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, qbg, d),
                lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((qbg, d), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(_ragged_kernel, page_size=page_size,
                              group=group, scale=scale),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((r, hk, qbg, d), q4.dtype),
            interpret=interpret,
        )(tables, kv_lens, q_starts, q_lens, q4, k_pages, v_pages)

    return call


def _ragged_impl_q8(q, k_pages, v_pages, k_scale, v_scale, block_tables,
                    kv_lens, q_starts, q_lens, scale):
    r, qb, h, d = q.shape
    hk = k_pages.shape[1]
    group = h // hk
    page_size = k_pages.shape[2]
    q4 = q.reshape(r, qb, hk, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, hk, qb * group, d)
    call = _make_ragged_q8(scale, page_size, qb, group, _interpret())
    tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                      k_pages.shape[0] - 1)
    out = call(q4, k_pages, v_pages, k_scale.astype(jnp.float32),
               v_scale.astype(jnp.float32), tables,
               kv_lens.astype(jnp.int32), q_starts.astype(jnp.int32),
               q_lens.astype(jnp.int32))
    return out.reshape(r, hk, qb, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, qb, h, d)


def _ragged_impl(q, k_pages, v_pages, block_tables, kv_lens, q_starts,
                 q_lens, scale):
    r, qb, h, d = q.shape
    hk = k_pages.shape[1]
    group = h // hk
    page_size = k_pages.shape[2]
    # [R, QB, Hk, G, D] -> [R, Hk, QB*G, D]: one MXU operand per
    # (row, kv-head) with the GQA group riding inside the query block
    q4 = q.reshape(r, qb, hk, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, hk, qb * group, d)
    call = _make_ragged(scale, page_size, qb, group, _interpret())
    # clamp table tails (see paged_attention): they feed the index map
    tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                      k_pages.shape[0] - 1)
    out = call(q4, k_pages, v_pages, tables, kv_lens.astype(jnp.int32),
               q_starts.astype(jnp.int32), q_lens.astype(jnp.int32))
    return out.reshape(r, hk, qb, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, qb, h, d)


def ragged_paged_attention(q, k_pages, v_pages, block_tables, kv_lens,
                           q_starts, q_lens, scale=None, k_scale=None,
                           v_scale=None):
    """Mixed prefill+decode attention over the paged pool (see module
    docstring). Tape-integrated but non-differentiable (serving path).
    Pass ``k_scale``/``v_scale`` sidecars ([P, Hk, page, 1] f32) with
    int8 pools — the kernel dequantizes inside its kv loop."""
    if not supported(q, k_pages, v_pages, block_tables, kv_lens,
                     q_starts, q_lens, k_scale, v_scale):
        raise ValueError(
            "ragged_paged_attention preconditions not met: need q "
            "[R,QB,H,D], pages [P,Hk,page,D] (page % 8 == 0, D % 8 == 0, "
            "D <= 256, H % Hk == 0), tables [R,max_pages], kv_lens/"
            "q_starts/q_lens [R]; int8 pools need BOTH k_scale/v_scale "
            "sidecars shaped [P,Hk,page,1]")
    d = getattr(q, "_data", q).shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    if k_scale is not None:
        def fn_q8(q, kp, vp, ks, vs, bt, kl, qs, ql):
            return _ragged_impl_q8(q, kp, vp, ks, vs, bt, kl, qs, ql, s)

        return run_op("ragged_paged_attention_q8", fn_q8,
                      (q, k_pages, v_pages, k_scale, v_scale,
                       block_tables, kv_lens, q_starts, q_lens),
                      differentiable=False)

    def fn(q, kp, vp, bt, kl, qs, ql):
        return _ragged_impl(q, kp, vp, bt, kl, qs, ql, s)

    return run_op("ragged_paged_attention", fn,
                  (q, k_pages, v_pages, block_tables, kv_lens, q_starts,
                   q_lens), differentiable=False)


# ----------------------------------------------------------------------
# fused KV page write (ROADMAP item 2, first stage): the page write of
# the current dispatch's tokens happens INSIDE the attention kernel —
# see the module docstring for the replay/ordering contract.
# ----------------------------------------------------------------------

def fused_rope_geometry_ok(head_dim):
    """Cheap static gate for the rope-fused kernel: Pallas must be
    importable and the head_dim even (the neox rotation splits it in
    half). The serving engine consults this at construction and
    demotes ``fused_rope`` to the PR-13 fused-KV path (never a crash,
    never an interpret-mode crawl through an unsupported lowering)
    when it fails."""
    return _HAS_PLTPU and head_dim % 2 == 0 and head_dim >= 2


def rope_tables(pos, head_dim, base):
    """Per-dispatch rotary sin/cos tables, one row per PACKED token:
    ``[T, D]`` f32 with the neox duplicated-half layout (``emb =
    concat([ang, ang])``). Bitwise the same values
    `fused_rotary_position_embedding` derives from ``position_ids`` —
    the single source of the angle formula, computed ONCE per dispatch
    and shared by every layer (fused kernel and unfused fallback
    alike). ``pos`` is any integer array; it is flattened to ``[T]``.
    Pure jnp — safe under jit/trace."""
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                     dtype=jnp.float32) / head_dim))
    ang = pos.reshape(-1).astype(jnp.float32)[:, None] * inv  # [T, D/2]
    emb = jnp.concatenate([ang, ang], axis=-1)                # [T, D]
    return jnp.sin(emb), jnp.cos(emb)


def fused_supported(q, new_k, new_v, k_pages, v_pages, block_tables,
                    kv_lens, q_starts, q_lens, w_starts, w_flats,
                    w_ends, dump_page, k_scale=None, v_scale=None,
                    rope_sin=None, rope_cos=None, qblock=None):
    """Preconditions of the fused kernel: everything `supported`
    checks, plus packed new-row operands ``new_k/new_v [T, Hk, D]``
    (T >= 1), per-row write metadata ``w_starts/w_flats/w_ends [R]``
    and a valid ``dump_page`` id (a page no live table references —
    grid steps with nothing to write dump their page-sized output
    there). With ``rope_sin``/``rope_cos`` (the rope-fused variant) q
    switches to the packed pre-rope ``[T, H, D]`` layout, the tables
    must be ``[T, D]`` and ``qblock`` (the row-block width) must be
    given explicitly."""
    if (rope_sin is None) != (rope_cos is None):
        return False
    if rope_sin is not None:
        qs = getattr(q, "_data", q).shape
        nk = getattr(new_k, "_data", new_k)
        bt = getattr(block_tables, "_data", block_tables)
        if len(qs) != 3 or len(nk.shape) != 3 or len(bt.shape) != 2:
            return False
        t, h, d = (int(x) for x in qs)
        if qblock is None or int(qblock) < 1 or t != nk.shape[0]:
            return False
        if not fused_rope_geometry_ok(d):
            return False
        want = (t, d)
        for tb in (rope_sin, rope_cos):
            if tuple(getattr(tb, "_data", tb).shape) != want:
                return False
        hk = getattr(k_pages, "_data", k_pages).shape[1]
        if hk == 0 or h % hk:
            return False
        # remaining checks ride the non-rope validation with a
        # shape-only proxy for the row-blocked q the metadata implies
        proxy = jax.ShapeDtypeStruct((bt.shape[0], int(qblock), h, d),
                                     jnp.float32)
        return fused_supported(proxy, new_k, new_v, k_pages, v_pages,
                               block_tables, kv_lens, q_starts, q_lens,
                               w_starts, w_flats, w_ends, dump_page,
                               k_scale, v_scale)
    if not supported(q, k_pages, v_pages, block_tables, kv_lens,
                     q_starts, q_lens, k_scale, v_scale):
        return False
    r = getattr(q, "_data", q).shape[0]
    p, hk, _, d = getattr(k_pages, "_data", k_pages).shape
    for a in (w_starts, w_flats, w_ends):
        if tuple(getattr(a, "_data", a).shape) != (r,):
            return False
    nk = getattr(new_k, "_data", new_k)
    nv = getattr(new_v, "_data", new_v)
    if len(nk.shape) != 3 or tuple(nk.shape) != tuple(nv.shape):
        return False
    t, nhk, nd = nk.shape
    if t < 1 or nhk != hk or nd != d:
        return False
    try:
        dp = int(dump_page)
    except (TypeError, ValueError):
        return False
    return 0 <= dp < p


def _fused_kernel(tables_ref, kv_lens_ref, q_starts_ref, q_lens_ref,
                  w_starts_ref, w_flats_ref, w_ends_ref,
                  q_ref, k_ref, v_ref, nk_ref, nv_ref,
                  o_ref, ko_ref, vo_ref,
                  acc_ref, m_ref, l_ref, *, page_size, group, scale,
                  pad):
    r = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = kv_lens_ref[r]
    ws = w_starts_ref[r]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _compute():
        # replay this dispatch's writes over the streamed page: slots
        # at positions [w_start, ctx) were produced by rows <= r of
        # THIS grid and must be read from the packed new rows, never
        # from HBM — a pipelined page fetch may legally race the
        # write-back. Chunks of one sequence are packed contiguously
        # in position order, so position pos lives at packed index
        # w_flat + pos - w_start (shifted by the left pad).
        tpad = nk_ref.shape[1]
        f0 = jnp.clip(w_flats_ref[r] + page_start - ws + pad, 0,
                      tpad - page_size)
        spos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)
        fresh = (spos >= ws) & (spos < ctx)
        k_pg = jnp.where(fresh, nk_ref[0, pl.ds(f0, page_size), :],
                         k_ref[0, 0])
        v_pg = jnp.where(fresh, nv_ref[0, pl.ds(f0, page_size), :],
                         v_ref[0, 0])

        q = q_ref[0, 0].astype(jnp.float32) * scale      # [QB*G, D]
        _softmax_accumulate(q, k_pg.astype(jnp.float32),
                            v_pg.astype(jnp.float32), page_start,
                            q_starts_ref[r], q_lens_ref[r], ctx, group,
                            acc_ref, m_ref, l_ref)

        # in-kernel page write: ONLY the sequence's last row of this
        # grid (kv_len == w_end) writes, exactly once per page — the
        # out index map routes every other step to the dump page. The
        # condition here must mirror `_fused_write_map` bit for bit: a
        # step whose map picked a real page MUST fully write the block.
        @pl.when((ctx == w_ends_ref[r]) & (page_start + page_size > ws)
                 & (q_lens_ref[r] > 0))
        def _writeback():
            ko_ref[0, 0] = k_pg.astype(ko_ref.dtype)
            vo_ref[0, 0] = v_pg.astype(vo_ref.dtype)

    @pl.when(p == num_pages - 1)
    def _finish():
        _softmax_finish(o_ref, acc_ref, l_ref)


def _quantize_rows(xf):
    """Per-slot symmetric int8 quantization of ``[page, D]`` f32 rows —
    bitwise the same math as `quantize_kv_int8` (absmax over D,
    ``maximum(amax, 1e-8) / 127``), returning the clipped integer
    values still in f32 (exact in f32; the caller casts to int8 for
    storage and multiplies by the scale for the dequantized read, which
    is bit-identical to storing int8 and dequantizing later). The
    reciprocal multiply (not a divide) matches `quantize_kv_int8`
    exactly — see the note there."""
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    sc = jnp.maximum(amax, 1e-8) * jnp.float32(1.0 / 127.0)
    q = jnp.clip(jnp.round(xf / sc), -127.0, 127.0)
    return q, sc


def _fused_kernel_q8(tables_ref, kv_lens_ref, q_starts_ref, q_lens_ref,
                     w_starts_ref, w_flats_ref, w_ends_ref,
                     q_ref, k_ref, v_ref, ks_ref, vs_ref, nk_ref, nv_ref,
                     o_ref, ko_ref, vo_ref, kso_ref, vso_ref,
                     acc_ref, m_ref, l_ref, *, page_size, group, scale,
                     pad):
    """Int8-pool fused variant: fresh rows are quantized IN the kernel
    (same bits as `_page_write_q8`'s `quantize_kv_int8`), the softmax
    reads their dequantized values, and the int8 page + scale sidecar
    write back together."""
    r = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = kv_lens_ref[r]
    ws = w_starts_ref[r]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _compute():
        tpad = nk_ref.shape[1]
        f0 = jnp.clip(w_flats_ref[r] + page_start - ws + pad, 0,
                      tpad - page_size)
        spos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)
        fresh = (spos >= ws) & (spos < ctx)
        k_qn, k_scn = _quantize_rows(
            nk_ref[0, pl.ds(f0, page_size), :].astype(jnp.float32))
        v_qn, v_scn = _quantize_rows(
            nv_ref[0, pl.ds(f0, page_size), :].astype(jnp.float32))
        # dequantized page view: fresh slots read quantize->dequantize
        # (NOT the raw float) so the fused step is bitwise what the
        # unfused engine computes after its quantizing scatter
        k = jnp.where(fresh, k_qn * k_scn,
                      k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0])
        v = jnp.where(fresh, v_qn * v_scn,
                      v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0])

        q = q_ref[0, 0].astype(jnp.float32) * scale
        _softmax_accumulate(q, k, v, page_start, q_starts_ref[r],
                            q_lens_ref[r], ctx, group, acc_ref, m_ref,
                            l_ref)

        @pl.when((ctx == w_ends_ref[r]) & (page_start + page_size > ws)
                 & (q_lens_ref[r] > 0))
        def _writeback():
            ko_ref[0, 0] = jnp.where(fresh, k_qn.astype(jnp.int8),
                                     k_ref[0, 0])
            vo_ref[0, 0] = jnp.where(fresh, v_qn.astype(jnp.int8),
                                     v_ref[0, 0])
            kso_ref[0, 0] = jnp.where(fresh, k_scn, ks_ref[0, 0])
            vso_ref[0, 0] = jnp.where(fresh, v_scn, vs_ref[0, 0])

    @pl.when(p == num_pages - 1)
    def _finish():
        _softmax_finish(o_ref, acc_ref, l_ref)


def _rot_half(x):
    """``rotate_half`` on the last (head_dim) axis — same values as
    `incubate.nn.functional._rotate_half` (neox pairing)."""
    h = x.shape[-1] // 2
    return jnp.concatenate([-x[..., h:], x[..., :h]], axis=-1)


def _rope_k_page(nk_ref, sin_ref, cos_ref, f0, page_size):
    """Rope one replay slice of the packed pre-rope K rows: the SAME
    ``f0`` offset picks the rows and their positions' sin/cos (the
    tables are padded identically), and the rotated rows cast back
    through the MODEL dtype — exactly `_apply_rope`'s output. Shared
    by the fp and q8 rope kernels so the parity-critical rotation
    chain lives in one place (like `_softmax_accumulate`)."""
    sin_k = sin_ref[pl.ds(f0, page_size), :]
    cos_k = cos_ref[pl.ds(f0, page_size), :]
    k_new = nk_ref[0, pl.ds(f0, page_size), :].astype(jnp.float32)
    return (k_new * cos_k + _rot_half(k_new) * sin_k) \
        .astype(nk_ref.dtype)


def _rope_q_block(q_ref, sin_ref, cos_ref, q_starts_ref, w_starts_ref,
                  w_flats_ref, r, pad, qblock, group, scale):
    """Load + rope + scale one row's query block from the packed
    pre-rope q: the row's tokens sit contiguously on the packed axis
    at ``w_flat + (q_start - w_start)`` — the same affine replay index
    the KV overlay uses, read with the already-prefetched scalars
    (this is what deletes the host-side ``_token_gather`` q pack).
    Returns the scaled f32 ``[QB*G, D]`` block the softmax consumes;
    called ONCE per (row, kv-head) — the result lives in VMEM scratch
    across the page loop."""
    tpad = q_ref.shape[1]
    f0q = jnp.clip(w_flats_ref[r] + q_starts_ref[r] - w_starts_ref[r]
                   + pad, 0, tpad - qblock)
    qv = q_ref[0, pl.ds(f0q, qblock), :, :].astype(jnp.float32)
    sin_q = sin_ref[pl.ds(f0q, qblock), :][:, None, :]
    cos_q = cos_ref[pl.ds(f0q, qblock), :][:, None, :]
    q_rot = (qv * cos_q + _rot_half(qv) * sin_q) \
        .astype(q_ref.dtype)                          # [QB, G, D]
    return q_rot.reshape(qblock * group, qv.shape[-1]) \
        .astype(jnp.float32) * scale                  # [QB*G, D]


def _fused_rope_kernel(tables_ref, kv_lens_ref, q_starts_ref,
                       q_lens_ref, w_starts_ref, w_flats_ref,
                       w_ends_ref, q_ref, k_ref, v_ref, nk_ref, nv_ref,
                       sin_ref, cos_ref, o_ref, ko_ref, vo_ref,
                       acc_ref, m_ref, l_ref, q_s, *, page_size, group,
                       scale, pad, qblock):
    """Rope-fused variant of `_fused_kernel`: q and new_k arrive
    PRE-rope in packed layouts (q ``[Hk, tpad, G, D]`` head-major,
    new_k ``[Hk, tpad, D]`` in the MODEL dtype), the sin/cos tables
    ride whole in VMEM aligned to the same padded packed axis, and the
    rotation — ``x * cos + rotate_half(x) * sin`` in f32, cast back to
    the model dtype — happens here, feeding bitwise the same values
    into the write/attention math the post-rope kernel would have been
    handed. No transcendentals in-kernel: the tables carry them, so
    Mosaic and XLA compute identical bits. The roped q block is
    computed ONCE per (row, kv-head) into the ``q_s`` scratch — it
    depends only on the row, never on the page step."""
    r = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        q_s[...] = _rope_q_block(q_ref, sin_ref, cos_ref, q_starts_ref,
                                 w_starts_ref, w_flats_ref, r, pad,
                                 qblock, group, scale)

    ctx = kv_lens_ref[r]
    ws = w_starts_ref[r]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _compute():
        tpad = nk_ref.shape[1]
        f0 = jnp.clip(w_flats_ref[r] + page_start - ws + pad, 0,
                      tpad - page_size)
        spos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)
        fresh = (spos >= ws) & (spos < ctx)
        # rope the fresh K rows in VMEM (shared chain: `_rope_k_page`),
        # then cast on to the pool dtype, matching what the unfused
        # scatter would have stored
        k_rot = _rope_k_page(nk_ref, sin_ref, cos_ref, f0, page_size)
        k_pg = jnp.where(fresh, k_rot.astype(ko_ref.dtype), k_ref[0, 0])
        v_pg = jnp.where(fresh, nv_ref[0, pl.ds(f0, page_size), :],
                         v_ref[0, 0])

        _softmax_accumulate(q_s[...], k_pg.astype(jnp.float32),
                            v_pg.astype(jnp.float32), page_start,
                            q_starts_ref[r], q_lens_ref[r], ctx, group,
                            acc_ref, m_ref, l_ref)

        @pl.when((ctx == w_ends_ref[r]) & (page_start + page_size > ws)
                 & (q_lens_ref[r] > 0))
        def _writeback():
            ko_ref[0, 0] = k_pg
            vo_ref[0, 0] = v_pg

    @pl.when(p == num_pages - 1)
    def _finish():
        _softmax_finish(o_ref, acc_ref, l_ref)


def _fused_rope_kernel_q8(tables_ref, kv_lens_ref, q_starts_ref,
                          q_lens_ref, w_starts_ref, w_flats_ref,
                          w_ends_ref, q_ref, k_ref, v_ref, ks_ref,
                          vs_ref, nk_ref, nv_ref, sin_ref, cos_ref,
                          o_ref, ko_ref, vo_ref, kso_ref, vso_ref,
                          acc_ref, m_ref, l_ref, q_s, *, page_size,
                          group, scale, pad, qblock):
    """Int8-pool rope-fused variant: rope the fresh rows (as in
    `_fused_rope_kernel`, incl. the model-dtype round trip), THEN
    quantize them in-kernel with bitwise `quantize_kv_int8` math —
    the quantizer consumes exactly what the unfused engine's
    post-rope `_page_write_q8` would."""
    r = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        q_s[...] = _rope_q_block(q_ref, sin_ref, cos_ref, q_starts_ref,
                                 w_starts_ref, w_flats_ref, r, pad,
                                 qblock, group, scale)

    ctx = kv_lens_ref[r]
    ws = w_starts_ref[r]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _compute():
        tpad = nk_ref.shape[1]
        f0 = jnp.clip(w_flats_ref[r] + page_start - ws + pad, 0,
                      tpad - page_size)
        spos = page_start + jax.lax.broadcasted_iota(
            jnp.int32, (page_size, 1), 0)
        fresh = (spos >= ws) & (spos < ctx)
        # shared rotation chain, then the exact f32 widening the
        # unfused engine's post-rope quantizer consumes
        k_rot = _rope_k_page(nk_ref, sin_ref, cos_ref, f0, page_size) \
            .astype(jnp.float32)
        k_qn, k_scn = _quantize_rows(k_rot)
        v_qn, v_scn = _quantize_rows(
            nv_ref[0, pl.ds(f0, page_size), :].astype(jnp.float32))
        k = jnp.where(fresh, k_qn * k_scn,
                      k_ref[0, 0].astype(jnp.float32) * ks_ref[0, 0])
        v = jnp.where(fresh, v_qn * v_scn,
                      v_ref[0, 0].astype(jnp.float32) * vs_ref[0, 0])

        _softmax_accumulate(q_s[...], k, v, page_start,
                            q_starts_ref[r], q_lens_ref[r], ctx, group,
                            acc_ref, m_ref, l_ref)

        @pl.when((ctx == w_ends_ref[r]) & (page_start + page_size > ws)
                 & (q_lens_ref[r] > 0))
        def _writeback():
            ko_ref[0, 0] = jnp.where(fresh, k_qn.astype(jnp.int8),
                                     k_ref[0, 0])
            vo_ref[0, 0] = jnp.where(fresh, v_qn.astype(jnp.int8),
                                     v_ref[0, 0])
            kso_ref[0, 0] = jnp.where(fresh, k_scn, ks_ref[0, 0])
            vso_ref[0, 0] = jnp.where(fresh, v_scn, vs_ref[0, 0])

    @pl.when(p == num_pages - 1)
    def _finish():
        _softmax_finish(o_ref, acc_ref, l_ref)


def _fused_write_map(page_size, dump_page):
    """Out-spec index map for the pool write-back: the page the step
    writes when it IS the sequence's last row and the page overlaps the
    dispatch's write span ``[w_start, kv_len)``, else ``dump_page``.
    Must mirror the kernels' ``_writeback`` condition exactly."""
    def wmap(ri, hi, pi, tables, kv_lens, q_starts, q_lens, w_starts,
             w_flats, w_ends):
        ctx = kv_lens[ri]
        written = (pi * page_size < ctx) \
            & ((pi + 1) * page_size > w_starts[ri]) \
            & (ctx == w_ends[ri]) & (q_lens[ri] > 0)
        return jnp.where(written, tables[ri, pi], dump_page), hi, 0, 0

    return wmap


@functools.lru_cache(maxsize=32)
def _make_fused(scale, page_size, qb, group, tpad, dump_page,
                interpret):
    wmap = _fused_write_map(page_size, dump_page)

    def call(q4, k_pages, v_pages, nk, nv, tables, kv_lens, q_starts,
             q_lens, w_starts, w_flats, w_ends):
        r, hk, qbg, d = q4.shape
        max_pages = tables.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=7,
            grid=(r, hk, max_pages),
            in_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                # the dispatch's packed new K/V rows ride whole in VMEM
                pl.BlockSpec((1, tpad, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0)),
                pl.BlockSpec((1, tpad, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d), wmap),
                pl.BlockSpec((1, 1, page_size, d), wmap),
            ],
            scratch_shapes=[
                pltpu.VMEM((qbg, d), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(_fused_kernel, page_size=page_size,
                              group=group, scale=scale, pad=page_size),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((r, hk, qbg, d), q4.dtype),
                jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
            ],
            # the pools pass through in place: inputs 0-6 are the
            # scalar-prefetch operands, 7 is q4, 8/9 the pools
            input_output_aliases={8: 1, 9: 2},
            interpret=interpret,
        )(tables, kv_lens, q_starts, q_lens, w_starts, w_flats, w_ends,
          q4, k_pages, v_pages, nk, nv)

    return call


@functools.lru_cache(maxsize=32)
def _make_fused_q8(scale, page_size, qb, group, tpad, dump_page,
                   interpret):
    # ONE routing map for pages AND scale sidecars: the kernel writes
    # a page's int8 block and its scale block under the same condition,
    # so their out-spec routing must be the same closure, not two that
    # could drift apart
    wmap = _fused_write_map(page_size, dump_page)

    def call(q4, k_pages, v_pages, k_scale, v_scale, nk, nv, tables,
             kv_lens, q_starts, q_lens, w_starts, w_flats, w_ends):
        r, hk, qbg, d = q4.shape
        max_pages = tables.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=7,
            grid=(r, hk, max_pages),
            in_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, 1),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, 1),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, tpad, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0)),
                pl.BlockSpec((1, tpad, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d), wmap),
                pl.BlockSpec((1, 1, page_size, d), wmap),
                pl.BlockSpec((1, 1, page_size, 1), wmap),
                pl.BlockSpec((1, 1, page_size, 1), wmap),
            ],
            scratch_shapes=[
                pltpu.VMEM((qbg, d), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(_fused_kernel_q8, page_size=page_size,
                              group=group, scale=scale, pad=page_size),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((r, hk, qbg, d), q4.dtype),
                jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
                jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
            ],
            input_output_aliases={8: 1, 9: 2, 10: 3, 11: 4},
            interpret=interpret,
        )(tables, kv_lens, q_starts, q_lens, w_starts, w_flats, w_ends,
          q4, k_pages, v_pages, k_scale, v_scale, nk, nv)

    return call


@functools.lru_cache(maxsize=32)
def _make_fused_rope(scale, page_size, qblock, group, tpad, dump_page,
                     interpret):
    wmap = _fused_write_map(page_size, dump_page)

    def call(qp, k_pages, v_pages, nk, nv, sin, cos, tables, kv_lens,
             q_starts, q_lens, w_starts, w_flats, w_ends):
        hk, _, g, d = qp.shape
        r = tables.shape[0]
        qbg = qblock * group
        max_pages = tables.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=7,
            grid=(r, hk, max_pages),
            in_specs=[
                # pre-rope packed q rides whole, head-major, per kv-head
                pl.BlockSpec((1, tpad, g, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, tpad, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0)),
                pl.BlockSpec((1, tpad, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0)),
                # the per-dispatch sin/cos tables are position-aligned
                # to the SAME padded packed axis and shared by every
                # grid step (constant index map -> fetched once)
                pl.BlockSpec((tpad, d),
                             lambda ri, hi, pi, *refs: (0, 0)),
                pl.BlockSpec((tpad, d),
                             lambda ri, hi, pi, *refs: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d), wmap),
                pl.BlockSpec((1, 1, page_size, d), wmap),
            ],
            scratch_shapes=[
                pltpu.VMEM((qbg, d), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                # the row's roped+scaled q block, computed once per
                # (row, kv-head) and reused across the page loop
                pltpu.VMEM((qbg, d), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(_fused_rope_kernel, page_size=page_size,
                              group=group, scale=scale, pad=page_size,
                              qblock=qblock),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((r, hk, qbg, d), qp.dtype),
                jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
            ],
            # inputs 0-6 scalar prefetch, 7 packed q, 8/9 the pools
            input_output_aliases={8: 1, 9: 2},
            interpret=interpret,
        )(tables, kv_lens, q_starts, q_lens, w_starts, w_flats, w_ends,
          qp, k_pages, v_pages, nk, nv, sin, cos)

    return call


@functools.lru_cache(maxsize=32)
def _make_fused_rope_q8(scale, page_size, qblock, group, tpad,
                        dump_page, interpret):
    wmap = _fused_write_map(page_size, dump_page)

    def call(qp, k_pages, v_pages, k_scale, v_scale, nk, nv, sin, cos,
             tables, kv_lens, q_starts, q_lens, w_starts, w_flats,
             w_ends):
        hk, _, g, d = qp.shape
        r = tables.shape[0]
        qbg = qblock * group
        max_pages = tables.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=7,
            grid=(r, hk, max_pages),
            in_specs=[
                pl.BlockSpec((1, tpad, g, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, 1),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, 1),
                             lambda ri, hi, pi, tables, *refs:
                             (tables[ri, pi], hi, 0, 0)),
                pl.BlockSpec((1, tpad, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0)),
                pl.BlockSpec((1, tpad, d),
                             lambda ri, hi, pi, *refs: (hi, 0, 0)),
                pl.BlockSpec((tpad, d),
                             lambda ri, hi, pi, *refs: (0, 0)),
                pl.BlockSpec((tpad, d),
                             lambda ri, hi, pi, *refs: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, qbg, d),
                             lambda ri, hi, pi, *refs: (ri, hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d), wmap),
                pl.BlockSpec((1, 1, page_size, d), wmap),
                pl.BlockSpec((1, 1, page_size, 1), wmap),
                pl.BlockSpec((1, 1, page_size, 1), wmap),
            ],
            scratch_shapes=[
                pltpu.VMEM((qbg, d), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                pltpu.VMEM((qbg, 1), jnp.float32),
                pltpu.VMEM((qbg, d), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(_fused_rope_kernel_q8,
                              page_size=page_size, group=group,
                              scale=scale, pad=page_size,
                              qblock=qblock),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((r, hk, qbg, d), qp.dtype),
                jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
                jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
                jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
            ],
            input_output_aliases={8: 1, 9: 2, 10: 3, 11: 4},
            interpret=interpret,
        )(tables, kv_lens, q_starts, q_lens, w_starts, w_flats, w_ends,
          qp, k_pages, v_pages, k_scale, v_scale, nk, nv, sin, cos)

    return call


def _pack_new_rows(new, t, page_size, tpad, dtype):
    """[T, Hk, D] packed rows -> [Hk, tpad, D] head-major with a
    page_size left pad, so the kernels' clipped affine slice
    ``pl.ds(w_flat + page_start - w_start + pad, page_size)`` is always
    in bounds whenever any slot of the page is fresh."""
    nk = jnp.swapaxes(new.astype(dtype), 0, 1)
    return jnp.pad(nk, ((0, 0), (page_size, tpad - t - page_size),
                        (0, 0)))


def _fused_impl(q, new_k, new_v, k_pages, v_pages, block_tables,
                kv_lens, q_starts, q_lens, w_starts, w_flats, w_ends,
                dump_page, scale):
    r, qb, h, d = q.shape
    hk = k_pages.shape[1]
    group = h // hk
    page_size = k_pages.shape[2]
    t = new_k.shape[0]
    tpad = -(-(t + 2 * page_size) // 8) * 8
    q4 = q.reshape(r, qb, hk, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, hk, qb * group, d)
    # cast packed rows to the POOL dtype before the kernel: a fresh
    # slot must read exactly what the unfused scatter would have
    # stored (write-as-pool-dtype, read back) for decode-bitwise parity
    nk = _pack_new_rows(new_k, t, page_size, tpad, k_pages.dtype)
    nv = _pack_new_rows(new_v, t, page_size, tpad, v_pages.dtype)
    call = _make_fused(scale, page_size, qb, group, tpad,
                       int(dump_page), _interpret())
    tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                      k_pages.shape[0] - 1)
    out, kp, vp = call(q4, k_pages, v_pages, nk, nv, tables,
                       kv_lens.astype(jnp.int32),
                       q_starts.astype(jnp.int32),
                       q_lens.astype(jnp.int32),
                       w_starts.astype(jnp.int32),
                       w_flats.astype(jnp.int32),
                       w_ends.astype(jnp.int32))
    out = out.reshape(r, hk, qb, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, qb, h, d)
    return out, kp, vp


def _fused_impl_q8(q, new_k, new_v, k_pages, v_pages, k_scale, v_scale,
                   block_tables, kv_lens, q_starts, q_lens, w_starts,
                   w_flats, w_ends, dump_page, scale):
    r, qb, h, d = q.shape
    hk = k_pages.shape[1]
    group = h // hk
    page_size = k_pages.shape[2]
    t = new_k.shape[0]
    tpad = -(-(t + 2 * page_size) // 8) * 8
    q4 = q.reshape(r, qb, hk, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, hk, qb * group, d)
    # f32 packed rows: the in-kernel quantizer consumes exactly what
    # `quantize_kv_int8` would (x.astype(f32))
    nk = _pack_new_rows(new_k, t, page_size, tpad, jnp.float32)
    nv = _pack_new_rows(new_v, t, page_size, tpad, jnp.float32)
    call = _make_fused_q8(scale, page_size, qb, group, tpad,
                          int(dump_page), _interpret())
    tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                      k_pages.shape[0] - 1)
    out, kp, vp, ks, vs = call(
        q4, k_pages, v_pages, k_scale.astype(jnp.float32),
        v_scale.astype(jnp.float32), nk, nv, tables,
        kv_lens.astype(jnp.int32), q_starts.astype(jnp.int32),
        q_lens.astype(jnp.int32), w_starts.astype(jnp.int32),
        w_flats.astype(jnp.int32), w_ends.astype(jnp.int32))
    out = out.reshape(r, hk, qb, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, qb, h, d)
    return out, kp, vp, ks, vs


def _pack_new_q(q, t, group, page_size, tpad):
    """Pre-rope packed q ``[T, H, D]`` -> ``[Hk, tpad, G, D]``
    head-major with the same page_size left pad as `_pack_new_rows`,
    so one affine offset addresses q rows, K/V rows and the sin/cos
    tables alike."""
    hk = q.shape[1] // group
    d = q.shape[-1]
    q4 = q.reshape(t, hk, group, d).transpose(1, 0, 2, 3)
    return jnp.pad(q4, ((0, 0), (page_size, tpad - t - page_size),
                        (0, 0), (0, 0)))


def _pack_rope_table(tb, t, page_size, tpad):
    return jnp.pad(tb.astype(jnp.float32),
                   ((page_size, tpad - t - page_size), (0, 0)))


def _rope_tpad(t, page_size, qblock):
    """Padded packed-axis length for the rope-fused kernel: the left
    pad is page_size (as in `_pack_new_rows`) and the right pad must
    cover BOTH the page-sized K replay slice and the qblock-sized q
    slice starting at the last packed token."""
    return -(-(t + page_size + max(page_size, qblock)) // 8) * 8


def _fused_rope_impl(q, new_k, new_v, k_pages, v_pages, block_tables,
                     kv_lens, q_starts, q_lens, w_starts, w_flats,
                     w_ends, rope_sin, rope_cos, dump_page, scale,
                     qblock):
    t, h, d = q.shape
    hk = k_pages.shape[1]
    group = h // hk
    page_size = k_pages.shape[2]
    r = block_tables.shape[0]
    tpad = _rope_tpad(t, page_size, qblock)
    # q and new_k stay in the MODEL dtype: the kernel ropes them in
    # f32 and casts back through the model dtype (the `_apply_rope`
    # output) before the pool-dtype store — new_v needs no rope and
    # pre-casts to the pool dtype exactly like the post-rope kernel
    qp = _pack_new_q(q, t, group, page_size, tpad)
    nk = _pack_new_rows(new_k, t, page_size, tpad, new_k.dtype)
    nv = _pack_new_rows(new_v, t, page_size, tpad, v_pages.dtype)
    sin = _pack_rope_table(rope_sin, t, page_size, tpad)
    cos = _pack_rope_table(rope_cos, t, page_size, tpad)
    call = _make_fused_rope(scale, page_size, qblock, group, tpad,
                            int(dump_page), _interpret())
    tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                      k_pages.shape[0] - 1)
    out, kp, vp = call(qp, k_pages, v_pages, nk, nv, sin, cos, tables,
                       kv_lens.astype(jnp.int32),
                       q_starts.astype(jnp.int32),
                       q_lens.astype(jnp.int32),
                       w_starts.astype(jnp.int32),
                       w_flats.astype(jnp.int32),
                       w_ends.astype(jnp.int32))
    out = out.reshape(r, hk, qblock, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, qblock, h, d)
    return out, kp, vp


def _fused_rope_impl_q8(q, new_k, new_v, k_pages, v_pages, k_scale,
                        v_scale, block_tables, kv_lens, q_starts,
                        q_lens, w_starts, w_flats, w_ends, rope_sin,
                        rope_cos, dump_page, scale, qblock):
    t, h, d = q.shape
    hk = k_pages.shape[1]
    group = h // hk
    page_size = k_pages.shape[2]
    r = block_tables.shape[0]
    tpad = _rope_tpad(t, page_size, qblock)
    # both packed rows keep the MODEL dtype: the kernel ropes k, round
    # trips through the model dtype and widens to f32 for the bitwise
    # `quantize_kv_int8` math (an exact widening — identical to the
    # post-rope kernel's f32 pack)
    qp = _pack_new_q(q, t, group, page_size, tpad)
    nk = _pack_new_rows(new_k, t, page_size, tpad, new_k.dtype)
    nv = _pack_new_rows(new_v, t, page_size, tpad, new_v.dtype)
    sin = _pack_rope_table(rope_sin, t, page_size, tpad)
    cos = _pack_rope_table(rope_cos, t, page_size, tpad)
    call = _make_fused_rope_q8(scale, page_size, qblock, group, tpad,
                               int(dump_page), _interpret())
    tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                      k_pages.shape[0] - 1)
    out, kp, vp, ks, vs = call(
        qp, k_pages, v_pages, k_scale.astype(jnp.float32),
        v_scale.astype(jnp.float32), nk, nv, sin, cos, tables,
        kv_lens.astype(jnp.int32), q_starts.astype(jnp.int32),
        q_lens.astype(jnp.int32), w_starts.astype(jnp.int32),
        w_flats.astype(jnp.int32), w_ends.astype(jnp.int32))
    out = out.reshape(r, hk, qblock, group, d).transpose(0, 2, 1, 3, 4) \
        .reshape(r, qblock, h, d)
    return out, kp, vp, ks, vs


def fused_ragged_paged_attention(q, new_k, new_v, k_pages, v_pages,
                                 block_tables, kv_lens, q_starts,
                                 q_lens, w_starts, w_flats, w_ends,
                                 dump_page, scale=None, k_scale=None,
                                 v_scale=None, rope_sin=None,
                                 rope_cos=None, qblock=None):
    """Ragged paged attention WITH the KV page write fused in (see
    module docstring): writes ``new_k/new_v [T, Hk, D]`` — the
    dispatch's packed post-rope K/V rows — into each row's pages inside
    the kernel and attends through them, returning
    ``(out, k_pages, v_pages)`` (plus updated scale sidecars on the q8
    path). Per-row write metadata: ``w_starts[r]`` is the first
    position of row r's sequence written by THIS dispatch,
    ``w_flats[r]`` that position's index on the packed token axis,
    ``w_ends[r]`` the sequence's final kv_len in this dispatch (so the
    last row owns the write-back). ``dump_page`` is a page id no live
    table references; steps with nothing to write dump there and its
    contents are undefined after the call.

    With ``rope_sin``/``rope_cos`` (per-dispatch ``[T, D]`` f32 tables
    from :func:`rope_tables`) the call is the ROPE-FUSED variant:
    ``q`` arrives PRE-rope in the packed ``[T, H, D]`` token layout
    (the kernel slices each row's contiguous tokens via the write
    metadata — no host-side row-block gather), ``new_k`` is the
    pre-rope packed K, and the rotation happens in VMEM before the
    write/attention math, bitwise the unfused
    `fused_rotary_position_embedding` chain. ``qblock`` (the row-block
    width the metadata was built for) is required, and the returned
    attention output keeps the ``[R, qblock, H, D]`` row-block
    layout."""
    if not fused_supported(q, new_k, new_v, k_pages, v_pages,
                           block_tables, kv_lens, q_starts, q_lens,
                           w_starts, w_flats, w_ends, dump_page,
                           k_scale, v_scale, rope_sin, rope_cos,
                           qblock):
        raise ValueError(
            "fused_ragged_paged_attention preconditions not met: the "
            "`ragged_paged_attention` contract, plus new_k/new_v "
            "[T,Hk,D] (T >= 1), w_starts/w_flats/w_ends [R] and a "
            "dump_page id inside the pool; the rope-fused variant "
            "additionally needs packed q [T,H,D], rope_sin/rope_cos "
            "[T,D] and an explicit qblock >= 1")
    d = getattr(q, "_data", q).shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    dp = int(dump_page)

    if rope_sin is not None:
        qb = int(qblock)
        if k_scale is not None:
            def fn_rope_q8(q, nk, nv, kp, vp, ks, vs, bt, kl, qs, ql,
                           wss, wfs, wes, rs, rc):
                return _fused_rope_impl_q8(q, nk, nv, kp, vp, ks, vs,
                                           bt, kl, qs, ql, wss, wfs,
                                           wes, rs, rc, dp, s, qb)

            return run_op("fused_rope_ragged_paged_attention_q8",
                          fn_rope_q8,
                          (q, new_k, new_v, k_pages, v_pages, k_scale,
                           v_scale, block_tables, kv_lens, q_starts,
                           q_lens, w_starts, w_flats, w_ends, rope_sin,
                           rope_cos), differentiable=False)

        def fn_rope(q, nk, nv, kp, vp, bt, kl, qs, ql, wss, wfs, wes,
                    rs, rc):
            return _fused_rope_impl(q, nk, nv, kp, vp, bt, kl, qs, ql,
                                    wss, wfs, wes, rs, rc, dp, s, qb)

        return run_op("fused_rope_ragged_paged_attention", fn_rope,
                      (q, new_k, new_v, k_pages, v_pages, block_tables,
                       kv_lens, q_starts, q_lens, w_starts, w_flats,
                       w_ends, rope_sin, rope_cos),
                      differentiable=False)

    if k_scale is not None:
        def fn_q8(q, nk, nv, kp, vp, ks, vs, bt, kl, qs, ql, wss, wfs,
                  wes):
            return _fused_impl_q8(q, nk, nv, kp, vp, ks, vs, bt, kl,
                                  qs, ql, wss, wfs, wes, dp, s)

        return run_op("fused_ragged_paged_attention_q8", fn_q8,
                      (q, new_k, new_v, k_pages, v_pages, k_scale,
                       v_scale, block_tables, kv_lens, q_starts,
                       q_lens, w_starts, w_flats, w_ends),
                      differentiable=False)

    def fn(q, nk, nv, kp, vp, bt, kl, qs, ql, wss, wfs, wes):
        return _fused_impl(q, nk, nv, kp, vp, bt, kl, qs, ql, wss, wfs,
                           wes, dp, s)

    return run_op("fused_ragged_paged_attention", fn,
                  (q, new_k, new_v, k_pages, v_pages, block_tables,
                   kv_lens, q_starts, q_lens, w_starts, w_flats,
                   w_ends), differentiable=False)


def fused_ragged_paged_attention_xla(q, new_k, new_v, k_pages, v_pages,
                                     block_tables, kv_lens, q_starts,
                                     q_lens, w_starts, w_flats, w_ends,
                                     dump_page, scale=None,
                                     k_scale=None, v_scale=None,
                                     rope_sin=None, rope_cos=None,
                                     qblock=None):
    """Write-THEN-read reference for the fused kernel: scatter every
    row's packed new K/V rows into the pools (host-built indices, rows
    applied in order — unambiguous last-writer-wins), then run the
    plain `ragged_paged_attention_xla` over the updated pools. Two
    dependent ops with sequential semantics are exactly what the fused
    kernel's in-grid replay must reproduce; concrete (non-traced)
    arrays only. Returns the same tuple as the fused kernel. The dump
    page is untouched here — its contents are undefined in the fused
    path, so parity checks must exclude it.

    With ``rope_sin``/``rope_cos`` this is the ROPE-then-write-then-
    read reference: apply the table-driven rotation to the packed
    pre-rope ``q [T, H, D]`` and ``new_k`` first (the unfused
    `_apply_rope` chain, bit for bit), gather q into ``[R, qblock]``
    row blocks via the write metadata, then proceed as above."""
    import numpy as np
    from ..inference.paged_cache import quantize_kv_int8

    unwrap = [getattr(a, "_data", a)
              for a in (q, new_k, new_v, k_pages, v_pages, block_tables,
                        kv_lens, q_starts, q_lens, w_starts, w_flats)]
    (q, new_k, new_v, k_pages, v_pages, block_tables, kv_lens,
     q_starts, q_lens, w_starts, w_flats) = unwrap
    if rope_sin is not None:
        sin = jnp.asarray(getattr(rope_sin, "_data", rope_sin),
                          jnp.float32)
        cos = jnp.asarray(getattr(rope_cos, "_data", rope_cos),
                          jnp.float32)

        @jax.jit
        def _rope(x):                       # [T, heads, D], table [T, D]
            # jitted ON PURPOSE: XLA contracts the mul+add chain into
            # an FMA under jit but not in eager dispatch (1-ulp
            # difference), and the Pallas kernel this reference is
            # proven against always runs as a jitted computation
            xf = x.astype(jnp.float32)
            out = xf * cos[:, None, :] + _rot_half(xf) * sin[:, None, :]
            return out.astype(x.dtype)

        q_rot = np.asarray(_rope(q))
        new_k = _rope(new_k)
        # pack the roped q into the row blocks the metadata implies:
        # row r's tokens sit at packed [w_flat + q_start - w_start, +n)
        r_rows = block_tables.shape[0]
        qb = int(qblock)
        qr = np.zeros((r_rows, qb) + q_rot.shape[1:], q_rot.dtype)
        ql_np = np.asarray(q_lens)
        for i in range(r_rows):
            n = int(ql_np[i])
            if n <= 0:
                continue
            f0 = int(np.asarray(w_flats)[i]) \
                + int(np.asarray(q_starts)[i]) \
                - int(np.asarray(w_starts)[i])
            qr[i, :n] = q_rot[f0:f0 + n]
        q = jnp.asarray(qr)
    ps = k_pages.shape[2]
    tables = np.asarray(jnp.clip(block_tables.astype(jnp.int32), 0,
                                 k_pages.shape[0] - 1))
    kv_np = np.asarray(kv_lens)
    ql_np = np.asarray(q_lens)
    qs_np = np.asarray(q_starts)
    ws_np = np.asarray(w_starts)
    wf_np = np.asarray(w_flats)
    quant = k_scale is not None
    if quant:
        ks = getattr(k_scale, "_data", k_scale).astype(jnp.float32)
        vs = getattr(v_scale, "_data", v_scale).astype(jnp.float32)
        qk, sk = quantize_kv_int8(new_k)
        qv, sv = quantize_kv_int8(new_v)
    hidx = np.arange(k_pages.shape[1])[None, :]
    for r in range(q.shape[0]):
        if ql_np[r] <= 0 or kv_np[r] <= 0:
            continue
        start, end = int(qs_np[r]), int(kv_np[r])
        pos = np.arange(start, end)
        pages = tables[r, pos // ps]
        offs = pos % ps
        f = int(wf_np[r]) + pos - int(ws_np[r])
        if quant:
            k_pages = k_pages.at[pages[:, None], hidx,
                                 offs[:, None]].set(qk[f])
            v_pages = v_pages.at[pages[:, None], hidx,
                                 offs[:, None]].set(qv[f])
            ks = ks.at[pages[:, None], hidx, offs[:, None], 0].set(sk[f])
            vs = vs.at[pages[:, None], hidx, offs[:, None], 0].set(sv[f])
        else:
            k_pages = k_pages.at[pages[:, None], hidx, offs[:, None]] \
                .set(new_k[f].astype(k_pages.dtype))
            v_pages = v_pages.at[pages[:, None], hidx, offs[:, None]] \
                .set(new_v[f].astype(v_pages.dtype))
    if quant:
        out = ragged_paged_attention_xla(q, k_pages, v_pages, tables,
                                         kv_lens, q_starts, q_lens,
                                         scale=scale, k_scale=ks,
                                         v_scale=vs)
        return out, k_pages, v_pages, ks, vs
    out = ragged_paged_attention_xla(q, k_pages, v_pages, tables,
                                     kv_lens, q_starts, q_lens,
                                     scale=scale)
    return out, k_pages, v_pages


def ragged_paged_attention_xla(q, k_pages, v_pages, block_tables,
                               kv_lens, q_starts, q_lens, scale=None,
                               k_scale=None, v_scale=None):
    """XLA reference path: gather every row's pages to a contiguous
    [R, S, Hk, D] window, apply the causal/ragged mask, softmax.
    Semantically identical to the kernel (zeros on padded query rows
    and inactive rows; int8 pools dequantized by the scale sidecars);
    used for parity tests and as the fallback where Pallas is
    unavailable."""
    q, k_pages, v_pages, block_tables, kv_lens, q_starts, q_lens = (
        getattr(a, "_data", a)
        for a in (q, k_pages, v_pages, block_tables, kv_lens, q_starts,
                  q_lens))
    r, qb, h, d = q.shape
    p, hk, page_size, _ = k_pages.shape
    group = h // hk
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    tables = jnp.clip(block_tables.astype(jnp.int32), 0, p - 1)
    if k_scale is not None:
        ks = getattr(k_scale, "_data", k_scale).astype(jnp.float32)
        vs = getattr(v_scale, "_data", v_scale).astype(jnp.float32)
        k_pages = k_pages.astype(jnp.float32) * ks
        v_pages = v_pages.astype(jnp.float32) * vs
    # [R, W, Hk, page, D] -> [R, S, Hk, D]
    k = jnp.swapaxes(k_pages[tables], 2, 3).reshape(r, -1, hk, d)
    v = jnp.swapaxes(v_pages[tables], 2, 3).reshape(r, -1, hk, d)
    kq = jnp.repeat(k, group, axis=2)
    vq = jnp.repeat(v, group, axis=2)
    logits = jnp.einsum("rqhd,rshd->rhqs", q.astype(jnp.float32),
                        kq.astype(jnp.float32)) * s
    S = k.shape[1]
    kpos = jnp.arange(S)[None, None, None, :]
    qpos = (q_starts[:, None] + jnp.arange(qb)[None, :])[:, None, :, None]
    qvalid = (jnp.arange(qb)[None, :]
              < q_lens[:, None])[:, None, :, None]
    mask = (kpos <= qpos) & (kpos < kv_lens[:, None, None, None]) & qvalid
    logits = jnp.where(mask, logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    # fully-masked rows (padding / inactive) -> zeros, matching the
    # kernel's l == 0 guard rather than softmax's uniform fallback
    any_valid = jnp.any(mask, axis=-1, keepdims=True)
    w = jnp.where(any_valid, w, 0.0)
    out = jnp.einsum("rhqs,rshd->rqhd", w, vq.astype(jnp.float32))
    return out.astype(q.dtype)
