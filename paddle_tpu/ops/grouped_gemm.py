"""Grouped GEMM: one Pallas kernel for every expert's ragged matmul.

Capability reference: the operator-fusion direction of *MPK*
(arXiv 2512.22219) and *Neptune* (arXiv 2510.08726) applied to MoE
dispatch — instead of a gather → per-expert einsum → scatter chain (or
a dense ``[E, C, D]`` one-hot dispatch einsum), ONE kernel walks every
expert's contiguous row block and runs its matmul against that expert's
weight, skipping experts with no rows and masking ragged block tails.
This is the kernel behind the rebuilt ragged MoE path
(`paddle_tpu/incubate/moe`) and the MoE serving FFN
(`paddle_tpu/models/llama.py` ``LlamaMoEMLP``).

Shapes (E experts, stride C rows per expert, M = E * C total rows):
  x            [M, K]     rows laid out expert-contiguous: expert ``e``
                          owns rows ``[e*C, (e+1)*C)``; only the first
                          ``group_sizes[e]`` of them are real — the
                          rest are padding the kernel never reads
                          (masked) and never writes (zeroed)
  w            [E, K, N]  stacked per-expert weights
  group_sizes  [E] int32  real rows per expert (0 <= gs[e] <= C); the
                          scalar-prefetch metadata — together with the
                          static stride it is the ``(group_start,
                          group_len)`` description of every expert's
                          row block
  -> y         [M, N]     y[e*C + i] = x[e*C + i] @ w[e] for
                          i < group_sizes[e], else 0

Semantics match ``grouped_gemm_xla`` exactly (same contraction, f32
accumulation): the XLA reference is the parity bar and the fallback
where the kernel's preconditions don't hold — the same contract as the
flash / paged / ragged attention kernels.

The kernel runs grid (E, MT, NT): the scalar-prefetched ``group_sizes``
decide, per (expert, row-tile), whether the MXU runs at all — an empty
expert's tiles (and every tile past an expert's last real row) write
zeros without touching the weights, and the x BlockSpec index map clamps
skipped tiles onto the expert's last active block so consecutive
skipped grid steps re-use the already-resident VMEM block instead of
streaming dead rows from HBM. Ragged tails (group_sizes[e] not a
multiple of the row tile) are masked inside the tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports on CPU too (interpret mode)
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..framework.tensor import run_op

__all__ = ["grouped_gemm", "grouped_gemm_xla", "supported",
           "grouped_gemm_q8", "grouped_gemm_q8_xla", "supported_q8"]

#: VMEM budget for one grid step's blocks (x tile + w tile + out tile),
#: kept well under the ~16 MB/core ceiling (see pallas_guide.md)
_VMEM_BUDGET = 12 * 1024 * 1024


def _interpret():
    return jax.default_backend() != "tpu"


def _shape_of(a):
    return tuple(getattr(a, "_data", a).shape)


def _blocks(c, k, n, itemsize):
    """(block_m, block_n) for the kernel grid: row tiles sublane-aligned
    and capped at 128; n tiles lane-sized when N allows."""
    bm = min(128, -(-c // 8) * 8)
    if n % 256 == 0:
        bn = 256
    elif n % 128 == 0:
        bn = 128
    else:
        bn = n          # one lane tile; N % 8 == 0 by supported()
    # shrink bn while a grid step's blocks exceed the VMEM budget
    while bn > 128 and (bm * k + k * bn + bm * bn) * itemsize \
            > _VMEM_BUDGET:
        bn //= 2
    return bm, bn


def supported(x, w, group_sizes):
    """Pallas-path preconditions: a TPU backend (off-chip the
    interpreter would be orders of magnitude slower than the XLA
    formulation, so CPU always takes the reference — the fallback
    contract the tests pin), x [M, K] with M a multiple of E,
    w [E, K, N], group_sizes [E]; K and N sublane/lane friendly; one
    grid step's blocks within the VMEM budget. Anything else takes
    :func:`grouped_gemm_xla`."""
    if not _HAS_PLTPU or _interpret():
        return False
    xs, ws, gs = _shape_of(x), _shape_of(w), _shape_of(group_sizes)
    if len(xs) != 2 or len(ws) != 3 or len(gs) != 1:
        return False
    m, k = xs
    e, kw, n = ws
    if e == 0 or gs[0] != e or kw != k:
        return False
    if m == 0 or m % e:
        return False
    if k % 8 or n % 8:
        return False
    c = m // e
    itemsize = jnp.dtype(getattr(x, "_data", x).dtype).itemsize
    bm, bn = _blocks(c, k, n, max(itemsize, 4))
    if (bm * k + k * bn + bm * bn) * max(itemsize, 4) > _VMEM_BUDGET:
        return False
    return True


def _gg_kernel(gs_ref, x_ref, w_ref, o_ref, *, block_m):
    e = pl.program_id(0)
    mi = pl.program_id(1)
    rows = gs_ref[e]

    @pl.when(mi * block_m < rows)
    def _compute():
        x = x_ref[0].astype(jnp.float32)                    # [BM, K]
        # mask the ragged tail: rows at or past group_sizes[e] are
        # padding (and, when C % BM != 0, Pallas pad garbage) — they
        # must contribute zeros, exactly like the XLA reference's mask
        ridx = mi * block_m + jax.lax.broadcasted_iota(
            jnp.int32, (block_m, 1), 0)
        x = jnp.where(ridx < rows, x, 0.0)
        o_ref[0] = jax.lax.dot_general(
            x, w_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(o_ref.dtype)

    @pl.when(mi * block_m >= rows)
    def _skip():
        # an empty expert / a tile fully past the group's last row:
        # no MXU work, defined zeros out
        o_ref[0] = jnp.zeros(o_ref.shape[1:], o_ref.dtype)


@functools.lru_cache(maxsize=64)
def _make_grouped(e, c, k, n, block_m, block_n, out_dtype, interpret):
    mt = -(-c // block_m)
    nt = -(-n // block_n)

    def x_index(ei, mi, ni, gs):
        # skipped tiles (mi past the expert's last real row) clamp onto
        # the expert's last ACTIVE block: consecutive skipped grid
        # steps keep the same block index, so the pipeline never
        # streams dead rows from HBM for them
        last = jnp.maximum(gs[ei] - 1, 0) // block_m
        return (ei, jnp.minimum(mi, last), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(e, mt, nt),
        in_specs=[
            pl.BlockSpec((1, block_m, k), x_index),
            pl.BlockSpec((1, k, block_n),
                         lambda ei, mi, ni, gs: (ei, 0, ni)),
        ],
        out_specs=pl.BlockSpec((1, block_m, block_n),
                               lambda ei, mi, ni, gs: (ei, mi, ni)),
    )

    def call(x3, w, gs):
        return pl.pallas_call(
            functools.partial(_gg_kernel, block_m=block_m),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((e, c, n), out_dtype),
            interpret=interpret,
        )(gs, x3, w)

    return call


def _grouped_impl(x, w, group_sizes):
    """Pallas dispatch (raw jax arrays). Caller guarantees
    :func:`supported`."""
    m, k = x.shape
    e, _, n = w.shape
    c = m // e
    bm, bn = _blocks(c, k, n, max(jnp.dtype(x.dtype).itemsize, 4))
    call = _make_grouped(e, c, k, n, bm, bn, x.dtype, _interpret())
    gs = jnp.clip(group_sizes.astype(jnp.int32), 0, c)
    return call(x.reshape(e, c, k), w, gs).reshape(m, n)


def _xla_impl(x, w, group_sizes):
    """XLA reference (raw jax arrays): mask each expert's padding rows,
    batch-matmul against the stacked weights. Semantically identical to
    the kernel (f32 accumulation, zeros on padded rows)."""
    m, k = x.shape
    e, _, n = w.shape
    c = m // e
    gs = jnp.clip(group_sizes.astype(jnp.int32), 0, c)
    x3 = x.reshape(e, c, k)
    mask = (jnp.arange(c, dtype=jnp.int32)[None, :] < gs[:, None])
    x3 = jnp.where(mask[..., None], x3.astype(jnp.float32), 0.0)
    y = jax.lax.dot_general(
        x3, w.astype(jnp.float32),
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    return y.astype(x.dtype).reshape(m, n)


@functools.lru_cache(maxsize=2)
def _grouped_vjp_fn(use_kernel):
    """Module-level custom-VJP grouped GEMM, one per impl choice.
    ``group_sizes`` is a PRIMAL (float0 cotangent), never a closure —
    a closed-over traced value would leak into the partial-eval
    jaxpr's constants and crash the backward lowering."""
    impl = _grouped_impl if use_kernel else _xla_impl

    @jax.custom_vjp
    def f(x, w, gs):
        return impl(x, w, gs)

    def fwd(x, w, gs):
        return f(x, w, gs), (x, w, gs)

    def bwd(res, g):
        x, w, gs0 = res
        m, k = x.shape
        e, _, n = w.shape
        c = m // e
        gs = jnp.clip(gs0.astype(jnp.int32), 0, c)
        # dx rows past group_sizes[e] must be zero (those x rows never
        # reached the output) — the grouped gemm against w^T masks
        # them. The transposed weight swaps K and N, so the forward's
        # supported() verdict does not transfer: re-select (a kernel
        # forward whose swapped shape blows the VMEM budget falls back
        # to XLA for dx), but never upgrade an XLA forward (the SPMD
        # path) to the kernel.
        dx = _grouped(g, jnp.swapaxes(w, 1, 2), gs0,
                      use_kernel=None if use_kernel else False)
        mask = (jnp.arange(c, dtype=jnp.int32)[None, :]
                < gs[:, None])[..., None]
        x3 = jnp.where(mask, x.reshape(e, c, k).astype(jnp.float32), 0.0)
        g3 = jnp.where(mask, g.reshape(e, c, n).astype(jnp.float32), 0.0)
        dw = jax.lax.dot_general(
            x3, g3, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32).astype(w.dtype)
        return (dx.astype(x.dtype), dw,
                np.zeros(gs0.shape, jax.dtypes.float0))

    f.defvjp(fwd, bwd)
    return f


def _grouped(x, w, group_sizes, use_kernel=None):
    """Raw-array grouped GEMM with a custom VJP — the building block
    the MoE layers trace over. ``use_kernel=None`` auto-selects the
    Pallas path when :func:`supported` holds; ``False`` forces the XLA
    formulation (the SPMD/expert-parallel path: GSPMD partitions the
    batched dot and inserts the dispatch collectives — a Pallas custom
    call would force replication)."""
    if use_kernel is None:
        use_kernel = supported(x, w, group_sizes)
    f = _grouped_vjp_fn(bool(use_kernel))
    return f(x, w, group_sizes.astype(jnp.int32))


def grouped_gemm(x, w, group_sizes):
    """Tensor-level grouped GEMM over expert-contiguous row blocks (see
    module docstring): ``y[e*C + i] = x[e*C + i] @ w[e]`` for
    ``i < group_sizes[e]``, zeros past each group's length. Dispatches
    the Pallas kernel when :func:`supported` holds, the XLA reference
    otherwise; differentiable (custom VJP: dx is a grouped GEMM against
    ``w^T``, dw a masked batched contraction)."""

    def fn(x, w, gs):
        return _grouped(x, w, gs)

    return run_op("grouped_gemm", fn, (x, w, group_sizes))


def grouped_gemm_xla(x, w, group_sizes):
    """XLA reference path (parity bar and non-Pallas fallback)."""

    def fn(x, w, gs):
        return _grouped(x, w, gs, use_kernel=False)

    return run_op("grouped_gemm_xla", fn, (x, w, group_sizes))


# ---------------------------------------------------------------------------
# int8 weight-only variant (paddle_tpu.quant): stacked expert weights
# stay int8 in HBM with per-block f32 scale sidecars [E, K/B, N]; the
# dequantize (upcast x scale) happens in VMEM right before each
# expert's dot. Serving-side only — quantized weights are frozen, so
# there is no VJP; the ragged row semantics (masking, skip, clamp) are
# identical to the float kernel above.
# ---------------------------------------------------------------------------

def _q8_dequant_w(w_q, scales, block):
    """Shared dequant expression (see quant.kernels._dequant_w): the
    kernel and the XLA formulation compute the SAME elementwise
    products, so both paths stay bitwise-identical."""
    k, n = w_q.shape[-2], w_q.shape[-1]
    kb = scales.shape[-2]
    shape = w_q.shape[:-2] + (kb, block, n)
    return (w_q.astype(jnp.float32).reshape(shape)
            * scales[..., :, None, :]).reshape(w_q.shape)


def _q8_vmem(bm, k, kb, bn, itemsize):
    return (bm * k * itemsize       # x tile
            + k * bn                # int8 weight tile
            + kb * bn * 4           # f32 scale tile
            + k * bn * 4            # dequantized f32 weight
            + bm * bn * 4)          # out tile


def supported_q8(x, w_q, scales, group_sizes, block):
    """Pallas-path preconditions for the int8 grouped GEMM: everything
    :func:`supported` checks, plus int8 weights, scales
    ``[E, K/B, N]`` tiling K exactly, and the (bigger — dequant temp)
    VMEM budget."""
    if not _HAS_PLTPU or _interpret():
        return False
    xs, ws, ss, gs = (_shape_of(x), _shape_of(w_q), _shape_of(scales),
                      _shape_of(group_sizes))
    if len(xs) != 2 or len(ws) != 3 or len(ss) != 3 or len(gs) != 1:
        return False
    m, k = xs
    e, kw, n = ws
    if e == 0 or gs[0] != e or kw != k:
        return False
    if m == 0 or m % e or k % 8 or n % 8:
        return False
    b = int(block)
    if b <= 0 or k % b:
        return False
    if ss != (e, k // b, n):
        return False
    qa = getattr(w_q, "_data", w_q)
    sa = getattr(scales, "_data", scales)
    if jnp.dtype(qa.dtype) != jnp.int8 \
            or jnp.dtype(sa.dtype) != jnp.float32:
        return False
    c = m // e
    itemsize = max(jnp.dtype(getattr(x, "_data", x).dtype).itemsize, 4)
    bm, bn = _blocks(c, k, n, itemsize)
    if n % bn:
        return False
    return _q8_vmem(bm, k, k // b, bn, itemsize) <= _VMEM_BUDGET


def _gg_q8_kernel(gs_ref, x_ref, w_ref, s_ref, o_ref, *, block_m,
                  block):
    e = pl.program_id(0)
    mi = pl.program_id(1)
    rows = gs_ref[e]

    @pl.when(mi * block_m < rows)
    def _compute():
        x = x_ref[0].astype(jnp.float32)                    # [BM, K]
        ridx = mi * block_m + jax.lax.broadcasted_iota(
            jnp.int32, (block_m, 1), 0)
        x = jnp.where(ridx < rows, x, 0.0)
        w = _q8_dequant_w(w_ref[0], s_ref[0], block)        # [K, BN]
        o_ref[0] = jax.lax.dot_general(
            x, w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(o_ref.dtype)

    @pl.when(mi * block_m >= rows)
    def _skip():
        o_ref[0] = jnp.zeros(o_ref.shape[1:], o_ref.dtype)


@functools.lru_cache(maxsize=64)
def _make_grouped_q8(e, c, k, n, kb, block, block_m, block_n,
                     out_dtype, interpret):
    mt = -(-c // block_m)
    nt = -(-n // block_n)

    def x_index(ei, mi, ni, gs):
        last = jnp.maximum(gs[ei] - 1, 0) // block_m
        return (ei, jnp.minimum(mi, last), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(e, mt, nt),
        in_specs=[
            pl.BlockSpec((1, block_m, k), x_index),
            pl.BlockSpec((1, k, block_n),
                         lambda ei, mi, ni, gs: (ei, 0, ni)),
            pl.BlockSpec((1, kb, block_n),
                         lambda ei, mi, ni, gs: (ei, 0, ni)),
        ],
        out_specs=pl.BlockSpec((1, block_m, block_n),
                               lambda ei, mi, ni, gs: (ei, mi, ni)),
    )

    def call(x3, w_q, scales, gs):
        return pl.pallas_call(
            functools.partial(_gg_q8_kernel, block_m=block_m,
                              block=block),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((e, c, n), out_dtype),
            interpret=interpret,
        )(gs, x3, w_q, scales)

    return call


def _q8_impl(x, w_q, scales, group_sizes, block):
    """Pallas dispatch (raw arrays). Caller guarantees
    :func:`supported_q8` (or forces interpret for the parity tests)."""
    m, k = x.shape
    e, _, n = w_q.shape
    c = m // e
    kb = scales.shape[1]
    bm, bn = _blocks(c, k, n, max(jnp.dtype(x.dtype).itemsize, 4))
    call = _make_grouped_q8(e, c, k, n, kb, int(block), bm, bn,
                            x.dtype, _interpret())
    gs = jnp.clip(group_sizes.astype(jnp.int32), 0, c)
    return call(x.reshape(e, c, k), w_q, scales, gs).reshape(m, n)


def _q8_xla_impl(x, w_q, scales, group_sizes, block):
    """XLA formulation: dequantize the stacked weights with the SAME
    elementwise expression the kernel uses, then the float reference's
    masked batched dot — exact parity by construction."""
    m, k = x.shape
    e, _, n = w_q.shape
    c = m // e
    gs = jnp.clip(group_sizes.astype(jnp.int32), 0, c)
    w = _q8_dequant_w(w_q, scales, int(block))
    x3 = x.reshape(e, c, k)
    mask = (jnp.arange(c, dtype=jnp.int32)[None, :] < gs[:, None])
    x3 = jnp.where(mask[..., None], x3.astype(jnp.float32), 0.0)
    y = jax.lax.dot_general(
        x3, w, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)
    return y.astype(x.dtype).reshape(m, n)


def _grouped_q8(x, w_q, scales, group_sizes, block, use_kernel=None):
    """Raw-array int8 grouped GEMM (no VJP — serving-only frozen
    weights). ``use_kernel=None`` auto-selects; ``True`` forces the
    kernel (interpret mode off-TPU: the parity tests); ``False`` the
    XLA formulation (the SPMD path)."""
    if use_kernel is None:
        use_kernel = supported_q8(x, w_q, scales, group_sizes, block)
    impl = _q8_impl if use_kernel else _q8_xla_impl
    return impl(x, w_q, scales, group_sizes.astype(jnp.int32),
                int(block))


def grouped_gemm_q8(x, w_q, scales, group_sizes, block):
    """Tensor-level int8 grouped GEMM: ``y[e*C + i] = x[e*C + i] @
    (w_q[e] * scales[e])`` for ``i < group_sizes[e]``, zeros past each
    group's length. Weights stay int8 in HBM (scale sidecars ride the
    same expert index); dequant happens in VMEM. Not differentiable."""

    def fn(x, w, s, gs):
        return _grouped_q8(x, w, s, gs, block)

    return run_op("grouped_gemm_q8", fn,
                  (x, w_q, scales, group_sizes), differentiable=False)


def grouped_gemm_q8_xla(x, w_q, scales, group_sizes, block):
    """XLA formulation of :func:`grouped_gemm_q8` (parity bar)."""

    def fn(x, w, s, gs):
        return _grouped_q8(x, w, s, gs, block, use_kernel=False)

    return run_op("grouped_gemm_q8_xla", fn,
                  (x, w_q, scales, group_sizes), differentiable=False)
