"""Justified exclusions: reference ops deliberately NOT in ops.yaml.

The completeness test (`tests/test_op_schema.py`) enforces that every
op in the reference's `paddle/phi/api/yaml/ops.yaml` +
`legacy_ops.yaml` is either in this framework's schema or listed here
with the reason. Categories:

- ``optimizer``: the reference registers each optimizer update rule as
  a mutating kernel; here updates are pure-functional steps inside the
  compiled train program (`paddle_tpu/optimizer/`), so there is no
  per-rule op to expose.
- ``collective``: `c_*` kernels are the reference's NCCL launch points;
  XLA emits collectives from GSPMD shardings, and the explicit API is
  `paddle_tpu.distributed.collective` (all_reduce/all_gather/...).
- ``ir-plumbing``: ops that exist to move values through the
  reference's static graph (assign/memcpy/data/full_int_array/...);
  jaxpr/StableHLO has first-class values, so they have no analog.
- ``covered``: capability exists under a different public name; the
  entry names it.
- ``amp``: loss-scaling bookkeeping lives in `paddle_tpu.amp.GradScaler`
  inside the compiled step.
- ``not-applicable``: hardware- or framework-specific (npu_identity).
"""

EXCLUSIONS = {
    # optimizer update kernels -> paddle_tpu.optimizer (pure steps)
    "adadelta_": ("optimizer", "optimizer.Adadelta.step()"),
    "adagrad_": ("optimizer", "optimizer.Adagrad.step()"),
    "adam_": ("optimizer", "optimizer.Adam.step()"),
    "adamax_": ("optimizer", "optimizer.Adamax.step()"),
    "adamw_": ("optimizer", "optimizer.AdamW.step()"),
    "asgd_": ("optimizer", "optimizer.SGD variants"),
    "lamb_": ("optimizer", "optimizer.Lamb.step()"),
    "momentum_": ("optimizer", "optimizer.Momentum.step()"),
    "rmsprop_": ("optimizer", "optimizer.RMSProp.step()"),
    "rprop_": ("optimizer", "optimizer.Rprop"),
    "sgd_": ("optimizer", "optimizer.SGD.step()"),
    "fused_adam_": ("optimizer", "one fused XLA step via jit.to_static"),
    "merged_adam_": ("optimizer", "same — XLA fuses the whole update"),
    "merged_momentum_": ("optimizer", "same"),
    "average_accumulates_": ("optimizer", "hapi/EMA accumulators"),
    # collective launch kernels -> GSPMD + distributed.collective
    "c_allgather": ("collective", "distributed.all_gather"),
    "c_allreduce_max": ("collective", "distributed.all_reduce(MAX)"),
    "c_allreduce_min": ("collective", "distributed.all_reduce(MIN)"),
    "c_allreduce_prod": ("collective", "distributed.all_reduce(PROD)"),
    "c_allreduce_sum": ("collective", "distributed.all_reduce(SUM)"),
    "c_broadcast": ("collective", "distributed.broadcast"),
    "c_concat": ("collective", "all_gather + concat"),
    "c_embedding": ("collective", "mp_layers.VocabParallelEmbedding"),
    "c_identity": ("collective", "GSPMD inserts identity/reshard"),
    "c_reduce_sum": ("collective", "distributed.reduce"),
    "c_sync_calc_stream": ("collective", "XLA orders streams itself"),
    "c_sync_comm_stream": ("collective", "XLA orders streams itself"),
    # static-graph IR plumbing -> first-class jaxpr values
    "assign_out_": ("ir-plumbing", "SSA values; no output aliasing op"),
    "assign_value_": ("ir-plumbing", "paddle.assign"),
    "coalesce_tensor": ("ir-plumbing", "XLA buffer assignment fuses"),
    "copy_to": ("ir-plumbing", "Tensor.to / device_put"),
    "data": ("ir-plumbing", "jit inputs are function args"),
    "full_": ("ir-plumbing", "Tensor.fill_"),
    "full_batch_size_like": ("ir-plumbing", "full_like on a slice"),
    "full_int_array": ("ir-plumbing", "python lists are trace constants"),
    "full_with_tensor": ("ir-plumbing", "paddle.full accepts tensors"),
    "gaussian_inplace": ("ir-plumbing", "normal_ method"),
    "uniform_inplace": ("ir-plumbing", "uniform_ method"),
    "memcpy_d2h": ("ir-plumbing", "jax.device_get"),
    "memcpy_h2d": ("ir-plumbing", "jax.device_put"),
    "merge_selected_rows": ("ir-plumbing", "no SelectedRows type; sparse "
                            "grads use BCOO"),
    "embedding_grad_dense": ("ir-plumbing", "autodiff emits the gather "
                             "gradient directly"),
    "set_value": ("covered", "Tensor.__setitem__ (tensor.manipulation)"),
    "set_value_with_tensor": ("covered", "Tensor.__setitem__"),
    "index_select_strided": ("ir-plumbing", "index_select handles it"),
    "repeat_interleave_with_tensor_index":
        ("covered", "repeat_interleave accepts tensor repeats"),
    "split_with_num": ("covered", "paddle.split(num_or_sections=int)"),
    "tensor_unfold": ("covered", "paddle.unfold"),
    "trans_layout": ("covered", "paddle.transpose"),
    "view_dtype": ("covered", "Tensor.view(dtype)"),
    "view_shape": ("covered", "Tensor.view(shape)"),
    "npu_identity": ("not-applicable", "NPU-specific"),
    # fft kernel triple -> public fft namespace
    "fft_c2c": ("covered", "paddle.fft.fft/ifft family"),
    "fft_c2r": ("covered", "paddle.fft.irfft family"),
    "fft_r2c": ("covered", "paddle.fft.rfft family"),
    # attention variants -> the flash/paged kernels
    "flash_attn_unpadded": ("covered", "flash_attention on ragged batch "
                            "via serving engine's bucketed prefill"),
    "flash_attn_with_sparse_mask": ("covered", "flash_attention + mask"),
    "memory_efficient_attention": ("covered", "ops.flash_attention"),
    "masked_multihead_attention_": ("covered", "ops.paged_attention "
                                    "decode kernel"),
    # fused epilogues XLA does on its own
    "conv2d_transpose_bias": ("covered", "conv2d_transpose(bias=...)"),
    "depthwise_conv2d": ("covered", "conv2d(groups=in_channels)"),
    "depthwise_conv2d_transpose": ("covered", "conv2d_transpose(groups)"),
    "fused_batch_norm_act": ("covered", "XLA fuses BN+act"),
    "fused_bn_add_activation": ("covered", "XLA fuses BN+add+act"),
    "fused_gemm_epilogue": ("covered", "XLA fuses matmul epilogues"),
    "fused_multi_transformer": ("covered", "incubate.nn "
                                "FusedTransformerEncoderLayer stack"),
    "sync_batch_norm_": ("covered", "nn.SyncBatchNorm over collectives"),
    "rnn": ("covered", "nn.layer.rnn RNN/LSTM/GRU (lax.scan)"),
    # quant legacy kernels -> paddle_tpu.quantization observers/QAT
    "apply_per_channel_scale": ("covered", "quantization.weight_quantize"),
    "dequantize_abs_max": ("covered", "quantization.weight_dequantize"),
    "dequantize_log": ("covered", "quantization observers"),
    "fake_quantize_abs_max": ("covered", "quantization.QAT fake-quant"),
    "fake_quantize_moving_average_abs_max": ("covered", "QAT observers"),
    "fake_quantize_range_abs_max": ("covered", "QAT observers"),
    # amp bookkeeping -> GradScaler state
    "check_finite_and_unscale_": ("amp", "amp.GradScaler.step"),
    "update_loss_scaling_": ("amp", "amp.GradScaler dynamic scaling"),
    "check_numerics": ("amp", "amp.debugging.check_numerics flag"),
    "enable_check_model_nan_inf": ("amp", "FLAGS_check_nan_inf"),
    "disable_check_model_nan_inf": ("amp", "FLAGS_check_nan_inf"),
    "accuracy_check": ("amp", "amp.debugging compare tools"),
    # graph sampling: host-side neighbor sampling utilities; the compute
    # path (message passing / segment ops) is in paddle_tpu.geometric
    "graph_khop_sampler": ("covered", "geometric sampling is host-side; "
                           "send_u_recv/segment ops are the device path"),
    "graph_sample_neighbors": ("covered", "same"),
    "weighted_sample_neighbors": ("covered", "same"),
    "reindex_graph": ("covered", "same"),
}
