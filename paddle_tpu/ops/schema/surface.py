"""Inventory of reference ops implemented as plain public functions.

The reference's `phi/api/yaml/ops.yaml` + `legacy_ops.yaml` list these
as ops; in this framework they are public functions that wrap ``run_op``
directly (variadic inputs, eager RNG draws, tuple returns — shapes that
don't fit the ``@defop`` template). Importing this module records each
one in the registry so the single-source schema (and the generated
``_C_ops`` surface) covers the full op inventory. Dispatch goes through
the same public autograd-aware function.
"""

from __future__ import annotations

import importlib

from ...tensor.registry import OPS, register_existing

#: (reference op name, module, attribute, records-grad)
_EXISTING = [
    ("add_n", "paddle_tpu.tensor.math", "add_n", True),
    ("amax", "paddle_tpu.tensor.math", "amax", True),
    ("amin", "paddle_tpu.tensor.math", "amin", True),
    ("remainder", "paddle_tpu.tensor.math", "remainder", True),
    ("scale", "paddle_tpu.tensor", "scale", True),
    ("arange", "paddle_tpu.tensor.creation", "arange", False),
    ("linspace", "paddle_tpu.tensor.creation", "linspace", False),
    ("logspace", "paddle_tpu.tensor.creation", "logspace", False),
    ("eye", "paddle_tpu.tensor.creation", "eye", False),
    ("empty", "paddle_tpu.tensor.creation", "empty", False),
    ("empty_like", "paddle_tpu.tensor.creation", "empty_like", False),
    ("zeros", "paddle_tpu.tensor.creation", "zeros", False),
    ("zeros_like", "paddle_tpu.tensor.creation", "zeros_like", False),
    ("ones", "paddle_tpu.tensor.creation", "ones", False),
    ("ones_like", "paddle_tpu.tensor.creation", "ones_like", False),
    ("full", "paddle_tpu.tensor.creation", "full", False),
    ("full_like", "paddle_tpu.tensor.creation", "full_like", False),
    ("meshgrid", "paddle_tpu.tensor.creation", "meshgrid", True),
    ("tril_indices", "paddle_tpu.tensor.creation", "tril_indices", False),
    ("triu_indices", "paddle_tpu.tensor.creation", "triu_indices", False),
    ("concat", "paddle_tpu.tensor.manipulation", "concat", True),
    ("stack", "paddle_tpu.tensor.manipulation", "stack", True),
    ("unstack", "paddle_tpu.tensor.manipulation", "unstack", True),
    ("broadcast_tensors", "paddle_tpu.tensor.manipulation",
     "broadcast_tensors", True),
    ("as_strided", "paddle_tpu.tensor.manipulation", "as_strided", True),
    ("unique", "paddle_tpu.tensor.manipulation", "unique", False),
    ("unique_consecutive", "paddle_tpu.tensor.manipulation",
     "unique_consecutive", False),
    ("topk", "paddle_tpu.tensor.search", "topk", True),
    ("kthvalue", "paddle_tpu.tensor.search", "kthvalue", True),
    ("mode", "paddle_tpu.tensor.search", "mode", True),
    ("nonzero", "paddle_tpu.tensor.search", "nonzero", False),
    ("top_p_sampling", "paddle_tpu.tensor.search", "top_p_sampling", False),
    ("multi_dot", "paddle_tpu.tensor.linalg", "multi_dot", True),
    ("is_empty", "paddle_tpu.tensor.logic", "is_empty", False),
    ("numel", "paddle_tpu.tensor.attribute", "numel", False),
    ("shape", "paddle_tpu.tensor.attribute", "shape", False),
    ("bernoulli", "paddle_tpu.tensor.random", "bernoulli", False),
    ("binomial", "paddle_tpu.tensor.random", "binomial", False),
    ("multinomial", "paddle_tpu.tensor.random", "multinomial", False),
    ("poisson", "paddle_tpu.tensor.random", "poisson", False),
    ("randint", "paddle_tpu.tensor.random", "randint", False),
    ("randperm", "paddle_tpu.tensor.random", "randperm", False),
    ("uniform", "paddle_tpu.tensor.random", "uniform", False),
    ("gaussian", "paddle_tpu.tensor.random", "gaussian", False),
    ("standard_gamma", "paddle_tpu.tensor.random", "standard_gamma", False),
    ("exponential_", "paddle_tpu.tensor.random", "exponential_", False),
    ("batch_norm", "paddle_tpu.nn.functional.norm", "batch_norm", True),
    ("dropout", "paddle_tpu.nn.functional.common", "dropout", True),
    ("gumbel_softmax", "paddle_tpu.nn.functional.activation",
     "gumbel_softmax", True),
    ("rrelu", "paddle_tpu.nn.functional.activation", "rrelu", True),
    ("softplus", "paddle_tpu.nn.functional.activation", "softplus", True),
    ("tanh_shrink", "paddle_tpu.nn.functional.activation", "tanhshrink",
     True),
    ("logsigmoid", "paddle_tpu.nn.functional.activation", "log_sigmoid",
     True),
    ("margin_cross_entropy", "paddle_tpu.nn.functional.loss",
     "margin_cross_entropy", True),
    ("nms", "paddle_tpu.vision.ops", "nms", False),
    ("roi_align", "paddle_tpu.vision.ops", "roi_align", True),
    ("roi_pool", "paddle_tpu.vision.ops", "roi_pool", True),
    ("frame", "paddle_tpu.signal", "frame", True),
    ("overlap_add", "paddle_tpu.signal", "overlap_add", True),
    ("send_u_recv", "paddle_tpu.geometric", "send_u_recv", True),
    ("send_ue_recv", "paddle_tpu.geometric", "send_ue_recv", True),
    ("send_uv", "paddle_tpu.geometric", "send_uv", True),
    ("swiglu", "paddle_tpu.incubate.nn.functional", "swiglu", True),
    ("class_center_sample", "paddle_tpu.nn.functional.common",
     "class_center_sample", False),
    ("reverse", "paddle_tpu.tensor.manipulation", "reverse", True),
    ("inverse", "paddle_tpu.tensor.linalg", "inv", True),
    ("kldiv_loss", "paddle_tpu.nn.functional.loss", "kl_div", True),
    ("bce_loss", "paddle_tpu.nn.functional.loss", "binary_cross_entropy",
     True),
    ("sigmoid_cross_entropy_with_logits", "paddle_tpu.nn.functional.loss",
     "binary_cross_entropy_with_logits", True),
    ("cross_entropy_with_softmax", "paddle_tpu.nn.functional.loss",
     "softmax_with_cross_entropy", True),
    ("warpctc", "paddle_tpu.nn.functional.loss", "ctc_loss", True),
    ("deformable_conv", "paddle_tpu.vision.ops", "deform_conv2d", True),
    ("flash_attn", "paddle_tpu.ops.flash_attention", "flash_attention",
     True),
    ("matrix_rank_tol", "paddle_tpu.tensor.linalg", "matrix_rank", False),
    ("segment_pool", "paddle_tpu.geometric", "segment_pool", True),
    ("accuracy", "paddle_tpu.metric", "accuracy", False),
    ("auc", "paddle_tpu.metric", "auc", False),
    ("truncated_gaussian_random", "paddle_tpu.tensor.random",
     "truncated_gaussian_random", False),
    ("dirichlet", "paddle_tpu.tensor.random", "dirichlet", False),
]


def register_surface():
    for op_name, mod_name, attr, diff in _EXISTING:
        if op_name in OPS:
            continue
        fn = getattr(importlib.import_module(mod_name), attr)
        register_existing(fn, op_name, differentiable=diff)


register_surface()
