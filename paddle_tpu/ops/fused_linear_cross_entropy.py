"""Chunked fused cross-entropy lm-head: loss without the [B*S, V] logits.

Capability reference: the locality-driven fusion direction of *Neptune*
(arXiv 2510.08726) applied to the training loss — `LlamaForCausalLM`
previously materialized the full ``[B*S, V]`` float32 logits tensor just
to reduce it to one scalar; at llama3-8b vocab (128256) that single
tensor and its softmax round trips dwarf every decoder layer's HBM
traffic. ``fused_linear_cross_entropy(hidden, lm_head_w, labels)``
computes the same mean next-token loss blockwise over vocab chunks (and
sequence tiles): per chunk, partial logits -> a running online logsumexp
and label-logit pick -> per-token loss, with a custom VJP that
RECOMPUTES each chunk's logits in the backward and emits
``d_hidden``/``d_w`` chunk by chunk — the ``[N, V]`` tensor never
exists in either pass.

Shapes (N = B*S tokens, D hidden, V vocab):
  hidden  [N, D]   (any float dtype; compute is f32-accumulated)
  w       [D, V]   the lm-head projection (``nn.Linear`` layout)
  labels  [N] int  next-token ids, ``ignore_index`` rows excluded from
                   the mean (the ``F.cross_entropy`` contract)
  -> loss scalar f32: ``sum(nll[valid]) / max(count(valid), 1)``

Three formulations, one contract:

- **Pallas kernel** where :func:`supported` holds (TPU backend, lane
  friendly D): grid ``(row-tiles, vocab-tiles)`` with the vocab index
  minor, so VMEM scratch carries each row tile's running
  ``(max, sumexp, label-logit)`` across that row's vocab sweep — one
  read of ``hidden``, one stream over ``w``, outputs ``[N]``.
- **chunked-XLA formulation** (the parity bar and the fallback
  everywhere else): the SAME online update unrolled over static vocab
  chunks. Math is identical op for op, so the kernel is testable
  against it at matching chunking.
- **SPMD formulation** when ``w`` is vocab-parallel sharded (the
  ``shard_llama`` lm-head layout): a single batched product with a
  ``with_sharding_constraint`` pinning the logits' vocab dim to the
  mesh axis — each device holds ``[N, V/mp]``, GSPMD partitions the
  logsumexp reduction (the ``mp_layers`` vocab-parallel embedding
  contract), and the mesh — not the chunk loop — bounds peak memory.

``PADDLE_TPU_FUSED_CE=0`` restores the materialized path in
``LlamaForCausalLM`` byte-for-byte; ``PADDLE_TPU_FUSED_CE_CHUNK``
(default 8192) sets the vocab chunk of the XLA formulation.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports on CPU too (interpret mode)
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..framework.tensor import run_op

__all__ = ["fused_linear_cross_entropy", "fused_linear_cross_entropy_xla",
           "supported"]

#: VMEM budget for one grid step's blocks (hidden tile + w tile + logits
#: tile, all f32), kept well under the ~16 MB/core ceiling
_VMEM_BUDGET = 12 * 1024 * 1024


def _interpret():
    return jax.default_backend() != "tpu"


def _shape_of(a):
    return tuple(getattr(a, "_data", a).shape)


def default_chunk():
    """Vocab chunk of the XLA formulation (env
    ``PADDLE_TPU_FUSED_CE_CHUNK``, default 8192)."""
    try:
        return max(8, int(os.environ.get("PADDLE_TPU_FUSED_CE_CHUNK",
                                         "8192")))
    except ValueError:
        return 8192


def _blocks(n, d, v):
    """(block_n, block_v) for the kernel grid: row tiles sublane-aligned
    and capped at 128 (the sequence tile), vocab tiles shrunk while one
    grid step's f32 blocks exceed the VMEM budget."""
    bn = min(128, -(-n // 8) * 8)
    bv = min(512, -(-v // 128) * 128)
    while bv > 128 and (bn * d + d * bv + bn * bv) * 4 > _VMEM_BUDGET:
        bv //= 2
    return bn, bv


def supported(hidden2d, w):
    """Pallas-path preconditions: a TPU backend (off-chip the interpreter
    would be orders of magnitude slower than the chunked XLA formulation,
    so CPU always takes the reference — the same fallback contract as
    ``grouped_gemm``), hidden [N, D] with D lane-aligned, w [D, V], and
    one grid step's blocks within the VMEM budget."""
    if not _HAS_PLTPU or _interpret():
        return False
    hs, ws = _shape_of(hidden2d), _shape_of(w)
    if len(hs) != 2 or len(ws) != 2:
        return False
    n, d = hs
    dw, v = ws
    if n == 0 or d == 0 or v == 0 or dw != d:
        return False
    if d % 128 or v < 128:
        return False
    bn, bv = _blocks(n, d, v)
    if (bn * d + d * bv + bn * bv) * 4 > _VMEM_BUDGET:
        return False
    return True


# ---------------------------------------------------------------------------
# chunked-XLA formulation: the parity bar (and the universal fallback)
# ---------------------------------------------------------------------------
def _xla_parts(h2d, w, labels, chunk):
    """(lse [N], pick [N]) via the online chunked logsumexp — the
    ``[N, V]`` logits never exist; peak extra memory is one ``[N, chunk]``
    f32 block. ``labels`` int32; rows whose label appears in no chunk
    (the ignore_index rows) get pick == 0, masked by the caller."""
    n, d = h2d.shape
    v = w.shape[1]
    h32 = h2d.astype(jnp.float32)
    m = jnp.full((n,), -jnp.inf, jnp.float32)
    s = jnp.zeros((n,), jnp.float32)
    pick = jnp.zeros((n,), jnp.float32)
    for lo in range(0, v, chunk):
        hi = min(lo + chunk, v)
        wc = jax.lax.slice_in_dim(w, lo, hi, axis=1).astype(jnp.float32)
        lg = jax.lax.dot_general(
            h32, wc, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [N, hi-lo]
        cm = jnp.max(lg, axis=1)
        m_new = jnp.maximum(m, cm)
        # first chunk: m == -inf so the rescale term is exactly 0 * 0
        s = s * jnp.exp(m - m_new) \
            + jnp.sum(jnp.exp(lg - m_new[:, None]), axis=1)
        m = m_new
        cols = lo + jnp.arange(hi - lo, dtype=jnp.int32)
        pick = pick + jnp.sum(
            jnp.where(cols[None, :] == labels[:, None], lg, 0.0), axis=1)
    return m + jnp.log(s), pick


# ---------------------------------------------------------------------------
# Pallas kernel: same math, one grid
# ---------------------------------------------------------------------------
def _ce_kernel(h_ref, w_ref, lab_ref, lse_ref, pick_ref, m_s, s_s, p_s,
               *, block_v, v):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        # fresh row tile: the general update below then matches the XLA
        # formulation's (-inf, 0, 0) start bit for bit
        m_s[...] = jnp.full(m_s.shape, -jnp.inf, jnp.float32)
        s_s[...] = jnp.zeros(s_s.shape, jnp.float32)
        p_s[...] = jnp.zeros(p_s.shape, jnp.float32)

    h = h_ref[...].astype(jnp.float32)                    # [BN, D]
    wb = w_ref[...].astype(jnp.float32)                   # [D, BV]
    lg = jax.lax.dot_general(
        h, wb, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)               # [BN, BV]
    # ragged vocab tail: pad columns past V contribute exp(-inf) == 0 to
    # the sum and never win the max, exactly like the XLA formulation's
    # exact-sized last chunk
    col = vi * block_v + jax.lax.broadcasted_iota(jnp.int32, lg.shape, 1)
    lg = jnp.where(col < v, lg, -jnp.inf)
    cm = jnp.max(lg, axis=1, keepdims=True)               # [BN, 1]
    m_old = m_s[...]
    m_new = jnp.maximum(m_old, cm)
    s_s[...] = s_s[...] * jnp.exp(m_old - m_new) \
        + jnp.sum(jnp.exp(lg - m_new), axis=1, keepdims=True)
    m_s[...] = m_new
    hit = col == lab_ref[...]                             # [BN, BV]
    p_s[...] = p_s[...] + jnp.sum(jnp.where(hit, lg, 0.0), axis=1,
                                  keepdims=True)

    @pl.when(vi == pl.num_programs(1) - 1)
    def _emit():
        lse_ref[...] = m_s[...] + jnp.log(s_s[...])
        pick_ref[...] = p_s[...]


@functools.lru_cache(maxsize=32)
def _make_ce_call(n, d, v, block_n, block_v, interpret):
    nt = -(-n // block_n)
    vt = -(-v // block_v)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(nt, vt),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((d, block_v), lambda ni, vi: (0, vi)),
            pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),
            pl.BlockSpec((block_n, 1), lambda ni, vi: (ni, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((block_n, 1), jnp.float32),
                        pltpu.VMEM((block_n, 1), jnp.float32),
                        pltpu.VMEM((block_n, 1), jnp.float32)],
    )

    def call(h2d, w, lab2d):
        return pl.pallas_call(
            functools.partial(_ce_kernel, block_v=block_v, v=v),
            grid_spec=grid_spec,
            out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.float32),
                       jax.ShapeDtypeStruct((n, 1), jnp.float32)],
            interpret=interpret,
        )(h2d, w, lab2d)

    return call


def _kernel_parts(h2d, w, labels, block_v=None):
    """Pallas dispatch (raw jax arrays) -> (lse [N], pick [N]). Caller
    guarantees :func:`supported` (tests pass ``block_v`` explicitly and
    run the interpreter off-TPU)."""
    n, d = h2d.shape
    v = w.shape[1]
    bn, bv = _blocks(n, d, v)
    if block_v is not None:
        bv = int(block_v)
    call = _make_ce_call(n, d, v, bn, bv, _interpret())
    lse2, pick2 = call(h2d, w, labels.reshape(n, 1))
    return lse2[:, 0], pick2[:, 0]


# ---------------------------------------------------------------------------
# custom VJP: backward recomputes each chunk's logits
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=8)
def _fused_ce_vjp_fn(use_kernel, chunk, ignore_index):
    """Module-level custom-VJP per-token nll, one per (impl, chunk,
    ignore) choice. ``labels`` is a PRIMAL (float0 cotangent), never a
    closure — the ``grouped_gemm`` contract: a closed-over traced value
    would leak into the partial-eval jaxpr's constants and crash the
    backward lowering."""

    def parts(h2d, w, lab):
        if use_kernel:
            return _kernel_parts(h2d, w, lab)
        return _xla_parts(h2d, w, lab, chunk)

    def nll_of(lse, pick, lab):
        return jnp.where(lab != ignore_index, lse - pick, 0.0)

    @jax.custom_vjp
    def f(h2d, w, lab):
        lse, pick = parts(h2d, w, lab)
        return nll_of(lse, pick, lab)

    def fwd(h2d, w, lab):
        lse, pick = parts(h2d, w, lab)
        return nll_of(lse, pick, lab), (h2d, w, lab, lse)

    def bwd(res, g):
        h2d, w, lab, lse = res
        n, d = h2d.shape
        v = w.shape[1]
        h32 = h2d.astype(jnp.float32)
        coef = jnp.where(lab != ignore_index,
                         g.astype(jnp.float32), 0.0)      # [N]
        dh = jnp.zeros((n, d), jnp.float32)
        # each vocab slot of d_w is written exactly once, so the chunks
        # land in ONE preallocated buffer via in-place slice updates —
        # a concatenate would keep every piece alive until the join
        dw = jnp.zeros((d, v), w.dtype)
        for lo in range(0, v, chunk):
            hi = min(lo + chunk, v)
            wc = jax.lax.slice_in_dim(w, lo, hi,
                                      axis=1).astype(jnp.float32)
            # recompute this chunk's logits: dlogits = (softmax -
            # onehot) * coef, so d_hidden/d_w accumulate chunk by chunk
            # and [N, V] never exists in the backward either
            lg = jax.lax.dot_general(
                h32, wc, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            p = jnp.exp(lg - lse[:, None])
            cols = lo + jnp.arange(hi - lo, dtype=jnp.int32)
            hot = (cols[None, :] == lab[:, None]).astype(jnp.float32)
            dlg = (p - hot) * coef[:, None]               # [N, hi-lo]
            dh = dh + jax.lax.dot_general(
                dlg, wc, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            dwc = jax.lax.dot_general(
                h32, dlg, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32).astype(w.dtype)
            dw = jax.lax.dynamic_update_slice(dw, dwc, (0, lo))
        return (dh.astype(h2d.dtype), dw,
                np.zeros(lab.shape, jax.dtypes.float0))

    f.defvjp(fwd, bwd)
    return f


def _loss_raw(h2d, w, lab, chunk, ignore_index, use_kernel):
    """Raw-array mean loss (the building block train steps trace over):
    ``sum(nll)/max(count, 1)``, the ``F.cross_entropy`` mean contract."""
    f = _fused_ce_vjp_fn(bool(use_kernel), int(chunk), int(ignore_index))
    lab = lab.astype(jnp.int32)
    nll = f(h2d, w, lab)
    valid = (lab != ignore_index).astype(jnp.float32)
    return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1.0)


def _spmd_loss_raw(h2d, w, lab, ignore_index, jax_mesh, axis):
    """Vocab-parallel SPMD formulation: ONE batched product whose vocab
    dim is constrained to the mesh axis carrying ``Shard(1)`` of ``w`` —
    each device materializes only its ``[N, V/mp]`` shard and GSPMD
    partitions the logsumexp/pick reductions (plain jax AD handles the
    backward; GSPMD partitions it the same way)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    lab = lab.astype(jnp.int32)
    h32 = h2d.astype(jnp.float32)
    lg = jax.lax.dot_general(
        h32, w.astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)               # [N, V] sharded
    lg = jax.lax.with_sharding_constraint(
        lg, NamedSharding(jax_mesh, P(P.UNCONSTRAINED, axis)))
    m = jnp.max(lg, axis=1)
    lse = m + jnp.log(jnp.sum(jnp.exp(lg - m[:, None]), axis=1))
    valid = lab != ignore_index
    safe = jnp.where(valid, lab, 0)
    pick = jnp.take_along_axis(lg, safe[:, None], axis=1)[:, 0]
    nll = jnp.where(valid, lse - pick, 0.0)
    denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return jnp.sum(nll) / denom


def _vocab_parallel_axis(weight):
    """(jax_mesh, axis_name) when ``weight`` [D, V] is annotated with a
    vocab Shard (tensor dim 1) over some mesh axis, else None."""
    if not getattr(weight, "is_dist", False):
        return None
    placements = getattr(weight, "_placements", None)
    mesh = getattr(weight, "_process_mesh", None)
    if not placements or mesh is None:
        return None
    for mesh_dim, p in enumerate(placements):
        if getattr(p, "is_shard", lambda d=None: False)(1):
            return mesh.to_jax_mesh(), mesh.dim_names[mesh_dim]
    return None


# ---------------------------------------------------------------------------
# Tensor-level entry points
# ---------------------------------------------------------------------------
def fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                               vocab_chunk=None):
    """Mean next-token cross entropy of ``hidden @ weight`` against
    ``labels`` without materializing the logits (module docstring).
    ``hidden`` [..., D] and ``labels`` [...] flatten together; returns a
    scalar f32 Tensor. Dispatches the Pallas kernel when
    :func:`supported` holds, the chunked XLA formulation otherwise, and
    the GSPMD vocab-parallel formulation when ``weight`` carries a
    vocab ``Shard`` annotation; differentiable (custom VJP on the
    chunked paths)."""
    spmd = _vocab_parallel_axis(weight)
    chunk = int(vocab_chunk) if vocab_chunk else default_chunk()

    def fn(h, w, lab):
        d = h.shape[-1]
        h2d = h.reshape((-1, d))
        lab1 = lab.reshape((-1,))
        if spmd is not None:
            return _spmd_loss_raw(h2d, w, lab1, ignore_index, *spmd)
        c = max(8, min(chunk, w.shape[1]))
        return _loss_raw(h2d, w, lab1, c, ignore_index,
                         supported(h2d, w))

    return run_op("fused_linear_cross_entropy", fn,
                  (hidden, weight, labels))


def fused_linear_cross_entropy_xla(hidden, weight, labels,
                                   ignore_index=-100, vocab_chunk=None):
    """Chunked-XLA formulation (parity bar and non-Pallas fallback)."""
    chunk = int(vocab_chunk) if vocab_chunk else default_chunk()

    def fn(h, w, lab):
        d = h.shape[-1]
        h2d = h.reshape((-1, d))
        c = max(8, min(chunk, w.shape[1]))
        return _loss_raw(h2d, w, lab.reshape((-1,)), c, ignore_index,
                         False)

    return run_op("fused_linear_cross_entropy_xla", fn,
                  (hidden, weight, labels))
