"""``paddle_tpu.ops`` — Pallas TPU kernels (the analog of the reference's
hand-fused kernel zoo `paddle/phi/kernels/fusion/`).

Kernels register behind ``FLAGS_use_pallas_kernels``; every op has an XLA
fallback in the functional layer, so this package is a pure acceleration
seam.
"""

from . import flash_attention  # noqa: F401
from . import fused_linear_cross_entropy  # noqa: F401
from . import grouped_gemm  # noqa: F401
from . import ragged_paged_attention  # noqa: F401
