"""Text datasets (reference: `python/paddle/text/datasets/`).

The reference auto-downloads corpora; this build runs with zero egress,
so every dataset takes ``data_file`` pointing at the same archive the
reference would download (formats identical — an aclImdb tar for
:class:`Imdb`, the simple-examples PTB tar for :class:`Imikolov`, the
whitespace table for :class:`UCIHousing`). Parsing, vocabulary building,
and example layout match the reference classes cited per dataset.
"""

from __future__ import annotations

import collections
import re
import string
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov"]


class UCIHousing(Dataset):
    """Boston-housing regression table (reference
    `text/datasets/uci_housing.py`): 14 whitespace-separated columns,
    features mean-centered and range-normalized over the full table,
    80/20 train/test split."""

    def __init__(self, data_file=None, mode="train", download=False):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        if data_file is None:
            raise ValueError(
                "data_file is required (no network in this build): pass "
                "the housing.data table the reference downloads")
        self.data_file = data_file
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maxs, mins, avgs = (data.max(0), data.min(0),
                            data.sum(0) / data.shape[0])
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (row[:-1].astype("float32"), row[-1:].astype("float32"))

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment corpus from the aclImdb tar (reference
    `text/datasets/imdb.py`): vocabulary of words with frequency >
    ``cutoff`` over train+test, docs as id arrays, label 0=pos 1=neg."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        if data_file is None:
            raise ValueError(
                "data_file is required (no network in this build): pass "
                "the aclImdb_v1.tar.gz archive the reference downloads")
        self.data_file = data_file
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        docs = []
        with tarfile.open(self.data_file) as tarf:
            member = tarf.next()
            while member is not None:
                if pattern.match(member.name):
                    docs.append(
                        tarf.extractfile(member).read()
                        .rstrip(b"\n\r")
                        .translate(None,
                                   string.punctuation.encode("latin-1"))
                        .lower().split())
                member = tarf.next()
        return docs

    def _build_word_dict(self, cutoff):
        freq = collections.defaultdict(int)
        pattern = re.compile(
            r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        for doc in self._tokenize(pattern):
            for w in doc:
                freq[w] += 1
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        # keys are bytes (tar payload); the reference mixes a str '<unk>'
        # into a bytes vocab — uniform bytes here
        word_idx[b"<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx[b"<unk>"]
        self.docs, self.labels = [], []
        for label, sub in ((0, "pos"), (1, "neg")):
            pattern = re.compile(rf"aclImdb/{self.mode}/{sub}/.*\.txt$")
            for doc in self._tokenize(pattern):
                self.docs.append(
                    [self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model corpus from the simple-examples tar (reference
    `text/datasets/imikolov.py`): vocabulary over train+valid with
    ``<s>``/``<e>`` markers, examples as N-grams or (src, trg) pairs."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=False):
        if data_type.upper() not in ("NGRAM", "SEQ"):
            raise ValueError(
                f"data_type should be 'NGRAM' or 'SEQ', got {data_type}")
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.data_type = data_type.upper()
        self.mode = mode.lower()
        self.window_size = window_size
        self.min_word_freq = min_word_freq
        if data_file is None:
            raise ValueError(
                "data_file is required (no network in this build): pass "
                "the simple-examples.tgz archive the reference downloads")
        self.data_file = data_file
        self.word_idx = self._build_word_dict(min_word_freq)
        self._load_anno()

    @staticmethod
    def _word_count(f, freq=None):
        freq = freq if freq is not None else collections.defaultdict(int)
        for line in f:
            for w in line.strip().split():
                freq[w] += 1
            freq[b"<s>"] += 1
            freq[b"<e>"] += 1
        return freq

    def _build_word_dict(self, cutoff):
        with tarfile.open(self.data_file) as tf:
            freq = self._word_count(
                tf.extractfile("./simple-examples/data/ptb.valid.txt"),
                self._word_count(
                    tf.extractfile("./simple-examples/data/ptb.train.txt")))
        freq.pop(b"<unk>", None)
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx[b"<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        self.data = []
        unk = self.word_idx[b"<unk>"]
        with tarfile.open(self.data_file) as tf:
            f = tf.extractfile(
                f"./simple-examples/data/ptb.{self.mode}.txt")
            for line in f:
                if self.data_type == "NGRAM":
                    if self.window_size < 0:
                        raise ValueError("NGRAM needs window_size > 0")
                    toks = [b"<s>"] + line.strip().split() + [b"<e>"]
                    if len(toks) < self.window_size:
                        continue
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    for i in range(self.window_size, len(ids) + 1):
                        self.data.append(
                            tuple(ids[i - self.window_size:i]))
                else:
                    ids = [self.word_idx.get(w, unk)
                           for w in line.strip().split()]
                    src = [self.word_idx[b"<s>"]] + ids
                    trg = ids + [self.word_idx[b"<e>"]]
                    if 0 < self.window_size < len(src):
                        continue
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)
