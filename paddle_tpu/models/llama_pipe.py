"""Pipeline-parallel Llama: stacked decoder weights over a ``pp`` mesh axis.

Reference capability: `fleet/meta_parallel/parallel_layers/pp_layers.py`
(``PipelineLayer``/``LayerDesc`` — model partitioning into stages) +
`pipeline_parallel.py:149` (the 1F1B engine driving it). TPU-native
re-design: every decoder layer's weights live in ONE stacked Parameter
``[L, ...]`` sharded ``Shard(0)`` over pp, and the schedule is the
compiled collective program in `distributed/pipeline.py`. Embedding, final
norm and lm-head run outside the pipelined region (replicated), exactly
like the reference ties them to the first/last stages.

The per-layer math mirrors `models/llama.py` (rms_norm fp32 accumulation,
neox rope, GQA attention with fp32 softmax) so ``from_dense`` weights give
loss parity with the dense model — the
`test/legacy_test/test_dist_base.py:952` bar.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..framework.tensor import Parameter, run_op
from ..framework import random as frandom
from ..nn import functional as F
from ..incubate.nn.functional import _default_sin_cos, _apply_rope
from ..tensor.registry import OPS
from .llama import LlamaConfig, _winit

__all__ = ["LlamaForCausalLMPipe"]


def _rms(x, w, eps):
    # the registered rms_norm core (fp32 accumulation) — same function the
    # dense model's nn.RMSNorm dispatches, so parity is by construction
    return OPS["rms_norm"]["fn"](x, weight=w, epsilon=eps)


def _layer_fwd(p, h, sin_e, cos_e, cfg: LlamaConfig):
    """One decoder layer, pure-jnp — same math as LlamaDecoderLayer."""
    nh, nkv, d = cfg.num_attention_heads, cfg.num_key_value_heads, \
        cfg.head_dim
    b, s = h.shape[0], h.shape[1]
    hs = _rms(h, p["ln1"], cfg.rms_norm_eps)
    q = jnp.matmul(hs, p["wq"]).reshape(b, s, nh, d)
    k = jnp.matmul(hs, p["wk"]).reshape(b, s, nkv, d)
    v = jnp.matmul(hs, p["wv"]).reshape(b, s, nkv, d)
    q = _apply_rope(q, sin_e, cos_e, True)   # neox, like the dense model
    k = _apply_rope(k, sin_e, cos_e, True)
    group = nh // nkv
    kr = jnp.repeat(k, group, axis=2).swapaxes(1, 2)    # [b, nh, s, d]
    vr = jnp.repeat(v, group, axis=2).swapaxes(1, 2)
    qh = q.swapaxes(1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kr,
                        preferred_element_type=jnp.float32) / math.sqrt(d)
    causal = jnp.tril(jnp.ones((s, s), dtype=bool))
    scores = jnp.where(causal, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(qh.dtype)
    attn = jnp.einsum("bhqk,bhkd->bqhd", probs, vr).reshape(b, s, nh * d)
    h = h + jnp.matmul(attn, p["wo"])
    h2 = _rms(h, p["ln2"], cfg.rms_norm_eps)
    mlp = jnp.matmul(
        jax.nn.silu(jnp.matmul(h2, p["wg"])) * jnp.matmul(h2, p["wu"]),
        p["wd"])
    return h + mlp


_PARAM_KEYS = ("wq", "wk", "wv", "wo", "wg", "wu", "wd", "ln1", "ln2")


class LlamaForCausalLMPipe(nn.Layer):
    """Decoder LM whose layer stack runs as a compiled pp pipeline."""

    def __init__(self, config: LlamaConfig, mesh, pp_axis="pp",
                 num_microbatches=2, remat=False, _init_stacked=True):
        super().__init__()
        from ..distributed import shard_tensor, Shard, Replicate

        if config.tie_word_embeddings:
            raise NotImplementedError(
                "LlamaForCausalLMPipe does not support tied embeddings "
                "yet: the embedding lives outside the pipelined region "
                "and the head cannot alias it across stages")
        self.config = config
        self.mesh = mesh
        self.pp_axis = pp_axis
        self.num_microbatches = num_microbatches
        self.remat = remat
        P = mesh.get_dim_size(pp_axis)
        L = config.num_hidden_layers
        if L % P:
            raise ValueError(f"{L} layers not divisible by {P} pp stages")

        hid, inter = config.hidden_size, config.intermediate_size
        nh, nkv, d = (config.num_attention_heads,
                      config.num_key_value_heads, config.head_dim)
        std = config.initializer_range

        def stacked(shape, ones=False):
            if ones:
                arr = jnp.ones((L,) + shape, jnp.float32)
            else:
                # framework RNG so paddle.seed() governs these weights,
                # like the dense model's Normal initializer
                arr = jax.random.normal(
                    frandom.next_key(), (L,) + shape, jnp.float32) * std
            p = Parameter(arr)
            place = [Replicate()] * mesh.ndim
            place[mesh.dim_names.index(pp_axis)] = Shard(0)
            return shard_tensor(p, mesh, place)

        if _init_stacked:
            self.wq = stacked((hid, nh * d))
            self.wk = stacked((hid, nkv * d))
            self.wv = stacked((hid, nkv * d))
            self.wo = stacked((nh * d, hid))
            self.wg = stacked((hid, inter))
            self.wu = stacked((hid, inter))
            self.wd = stacked((inter, hid))
            self.ln1 = stacked((hid,), ones=True)
            self.ln2 = stacked((hid,), ones=True)

        wa = _winit(config)
        self.embed_tokens = nn.Embedding(config.vocab_size, hid,
                                         weight_attr=wa)
        self.norm = nn.RMSNorm(hid, epsilon=config.rms_norm_eps)
        self.lm_head = nn.Linear(hid, config.vocab_size, weight_attr=wa,
                                 bias_attr=False)
        self._pipe_fns = {}   # seq_len -> pipelined middle fn (stable ids)

    # -- the pipelined middle -----------------------------------------------
    def _build_pipe_fn(self, seq_len):
        from ..distributed.pipeline import pipeline_spmd

        cfg, mesh, axis = self.config, self.mesh, self.pp_axis
        M, remat = self.num_microbatches, self.remat
        sin, cos = _default_sin_cos(seq_len, cfg.head_dim, cfg.rope_theta)
        sin_e = sin[None, :, None, :]
        cos_e = cos[None, :, None, :]

        def stage_fn(params, h):
            def body(hc, p):
                return _layer_fwd(p, hc, sin_e, cos_e, cfg), None
            h, _ = jax.lax.scan(body, h, params)
            return h

        def pipe(*arrays):
            params = dict(zip(_PARAM_KEYS, arrays[:-1]))
            return pipeline_spmd(stage_fn, params, arrays[-1], mesh=mesh,
                                 axis=axis, num_microbatches=M, remat=remat,
                                 watch_name="llama_pipe.pipeline")

        return pipe

    def forward(self, input_ids, labels=None):
        s = input_ids.shape[1]
        # dict cache: pipe fns (and the stage_fn closures keying the
        # compiled pipeline) stay stable per seq_len — alternating lengths
        # must not re-lower the pipeline
        fn = self._pipe_fns.get(s)
        if fn is None:
            fn = self._pipe_fns[s] = self._build_pipe_fn(s)
        x = self.embed_tokens(input_ids)
        x = run_op("llama_pipeline", fn,
                   (self.wq, self.wk, self.wv, self.wo, self.wg, self.wu,
                    self.wd, self.ln1, self.ln2, x))
        x = self.norm(x)
        logits = self.lm_head(x)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]).astype("float32"),
            labels.reshape([-1]), ignore_index=-100)
        return loss, logits

    # -- interop with the dense model ---------------------------------------
    @classmethod
    def from_dense(cls, dense, mesh, pp_axis="pp", num_microbatches=2,
                   remat=False):
        """Build a pipe model carrying the dense model's exact weights."""
        from ..distributed import shard_tensor, Shard, Replicate

        pipe = cls(dense.config, mesh, pp_axis, num_microbatches, remat,
                   _init_stacked=False)
        layers = dense.model.layers

        def stack(get):
            return np.stack([get(l) for l in layers], axis=0)

        mapping = {
            "wq": stack(lambda l: l.self_attn.q_proj.weight.numpy()),
            "wk": stack(lambda l: l.self_attn.k_proj.weight.numpy()),
            "wv": stack(lambda l: l.self_attn.v_proj.weight.numpy()),
            "wo": stack(lambda l: l.self_attn.o_proj.weight.numpy()),
            "wg": stack(lambda l: l.mlp.gate_proj.weight.numpy()),
            "wu": stack(lambda l: l.mlp.up_proj.weight.numpy()),
            "wd": stack(lambda l: l.mlp.down_proj.weight.numpy()),
            "ln1": stack(lambda l: l.input_layernorm.weight.numpy()),
            "ln2": stack(lambda l: l.post_attention_layernorm.weight.numpy()),
        }
        place = [Replicate()] * mesh.ndim
        place[mesh.dim_names.index(pp_axis)] = Shard(0)
        for key, arr in mapping.items():
            setattr(pipe, key, shard_tensor(Parameter(arr), mesh, place))
        pipe.embed_tokens.weight.set_value(
            dense.model.embed_tokens.weight.numpy())
        pipe.norm.weight.set_value(dense.model.norm.weight.numpy())
        if dense.lm_head is not None:
            pipe.lm_head.weight.set_value(dense.lm_head.weight.numpy())
        return pipe
