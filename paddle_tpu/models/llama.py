"""Llama decoder family (the BASELINE.md north-star model).

Capability reference: the reference framework trains Llama via PaddleNLP on
top of the fused ops in `python/paddle/incubate/nn/functional/` (swiglu,
fused_rms_norm, fused_rotary_position_embedding) and flash attention
(`python/paddle/nn/functional/flash_attention.py:147`). This module is the
TPU-native recipe built on the same in-tree pieces:

- pre-norm decoder blocks: RMSNorm -> GQA attention (+rope) -> RMSNorm ->
  SwiGLU MLP, all through the eager tape so one definition serves eager
  debugging and ``jit.to_static`` whole-step compilation;
- attention dispatches to the Pallas GQA flash kernel when shapes allow
  (`paddle_tpu/ops/flash_attention.py`), XLA fallback otherwise;
- :func:`shard_llama` annotates every weight with (tp, fsdp) placements
  over a ``ProcessMesh`` — GSPMD inserts the Megatron collectives
  (column/row linear all-gather + psum, vocab-parallel embedding) from the
  layout alone, the TPU analog of the reference's
  `fleet/layers/mpu/mp_layers.py`.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..nn import functional as F
from ..framework.tensor import Tensor
from ..incubate.nn import functional as FI
from ..nn.initializer import Normal

__all__ = ["LlamaConfig", "LlamaMLP", "LlamaMoEMLP", "LlamaAttention",
           "LlamaDecoderLayer", "LlamaModel", "LlamaForCausalLM",
           "shard_llama", "llama3_8b_config", "tiny_llama_config"]


@dataclasses.dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    initializer_range: float = 0.02
    #: checkpoint each decoder layer (training fwd): activations
    #: recompute in the backward sweep, trading ~1 extra forward for
    #: O(L) -> O(1) layer-activation memory (bigger batch/seq fits)
    recompute: bool = False
    #: > 0 selects the mixture-of-experts FFN (:class:`LlamaMoEMLP`,
    #: Mixtral-style) in every decoder layer: stacked ``[E, ...]``
    #: expert weights, dropless top-``moe_top_k`` routing through the
    #: grouped-GEMM kernel. 0 keeps the dense SwiGLU :class:`LlamaMLP`.
    moe_num_experts: int = 0
    moe_top_k: int = 2
    #: per-expert FFN width; None reuses ``intermediate_size``
    moe_intermediate_size: int | None = None

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def llama3_8b_config():
    """Llama-3-8B: GQA 32q/8kv, 128k vocab, rope theta 500k."""
    return LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        max_position_embeddings=8192, rms_norm_eps=1e-5, rope_theta=500000.0)


def tiny_llama_config(**kw):
    """A few-thousand-param config for tests and dry runs."""
    base = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=256,
                rope_theta=10000.0)
    base.update(kw)
    return LlamaConfig(**base)


def _winit(cfg):
    return Normal(mean=0.0, std=cfg.initializer_range)


def _kv_cache_update(buf, new, start):
    """Write ``new`` [B, s, Hk, D] into ``buf`` [B, max_len, Hk, D] at
    sequence offset ``start`` (a scalar int Tensor, traced-safe)."""
    import jax
    import jax.numpy as jnp
    from ..framework.tensor import run_op

    s, max_len = new.shape[1], buf.shape[1]
    start_arr = start._data if hasattr(start, "_data") else start
    if not isinstance(start_arr, jax.core.Tracer) \
            and int(start_arr) + s > max_len:
        # dynamic_update_slice would silently clamp the start and corrupt
        # the newest cached positions — refuse instead
        raise ValueError(
            f"KV cache overflow: writing {s} tokens at offset "
            f"{int(start_arr)} exceeds the static buffer ({max_len})")

    def fn(b, n, st):
        zero = jnp.zeros((), jnp.int32)
        return jax.lax.dynamic_update_slice(
            b, n.astype(b.dtype), (zero, jnp.asarray(st, jnp.int32),
                                   zero, zero))

    return run_op("kv_cache_update", fn, (buf, new, start))


def _decode_mask(length, s, max_len):
    """Bool [1, 1, s, max_len]: query i (absolute pos length+i) sees key j
    iff j <= length + i — causal over the valid prefix of a static
    buffer."""
    import jax.numpy as jnp
    from ..framework.tensor import run_op

    def fn(ln):
        qpos = jnp.asarray(ln, jnp.int32) + jnp.arange(s, dtype=jnp.int32)
        kpos = jnp.arange(max_len, dtype=jnp.int32)
        return (kpos[None, :] <= qpos[:, None])[None, None]

    return run_op("decode_mask", fn, (length,), differentiable=False)


class LlamaMLP(nn.Layer):
    """SwiGLU MLP: down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        wa = _winit(config)
        self.gate_proj = nn.Linear(config.hidden_size,
                                   config.intermediate_size,
                                   weight_attr=wa, bias_attr=False)
        self.up_proj = nn.Linear(config.hidden_size,
                                 config.intermediate_size,
                                 weight_attr=wa, bias_attr=False)
        self.down_proj = nn.Linear(config.intermediate_size,
                                   config.hidden_size,
                                   weight_attr=wa, bias_attr=False)

    def forward(self, x):
        return self.down_proj(FI.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaMoEMLP(nn.Layer):
    """Mixture-of-experts SwiGLU FFN (Mixtral-style), selected by
    ``config.moe_num_experts > 0``.

    Per token: softmax router over ``E`` experts, top-``k`` selection
    with renormalized weights, each expert a bias-free SwiGLU MLP with
    stacked ``[E, ...]`` weights. Routing is **dropless** (capacity =
    the token count, which an expert's load can never exceed), so the
    output of every token is a pure function of that token's hidden
    state — independent of how a batch is packed. That invariance is
    what lets the serving engine's token-packed mixed program emit
    greedy tokens EXACTLY equal to the plain ``LlamaForCausalLM``
    forward: pad/trash tokens route somewhere, but never into another
    token's output.

    Compute rides the grouped-GEMM megakernel
    (:mod:`paddle_tpu.ops.grouped_gemm`): one gather lays token-choices
    out expert-contiguous, three grouped GEMMs (gate/up/down) walk the
    ragged per-expert row blocks, one gather combines back. The
    per-token-count forward compiles through the ``moe_mlp`` compile
    watch (bounded LRU, same contract as ``MoELayer``).
    """

    FN_CACHE_SIZE = 8

    def __init__(self, config: LlamaConfig):
        super().__init__()
        import collections

        from ..framework import random as frandom
        from ..framework.tensor import Parameter

        e = int(config.moe_num_experts)
        if e <= 0:
            raise ValueError("LlamaMoEMLP needs config.moe_num_experts "
                             f"> 0, got {e}")
        self.num_experts = e
        self.top_k = max(1, min(int(config.moe_top_k), e))
        self.d_model = config.hidden_size
        self.d_ff = config.moe_intermediate_size \
            or config.intermediate_size
        std = config.initializer_range

        def init(shape):
            return Parameter(jax.random.normal(
                frandom.next_key(), shape, jnp.float32) * std)

        self.gate = init((self.d_model, e))
        self.gate_proj = init((e, self.d_model, self.d_ff))
        self.up_proj = init((e, self.d_model, self.d_ff))
        self.down_proj = init((e, self.d_ff, self.d_model))
        self.l_aux = None
        #: set by shard_llama: sharded expert weights must take the
        #: GSPMD-partitionable XLA formulation (a Pallas custom call
        #: would pin execution to one replica)
        self.sharded = False
        #: set by quantize_weights: the per-block size of the int8
        #: expert weights (None/0 = float weights, the default)
        self.weight_block = None
        self._fns: "dict[int, object]" = collections.OrderedDict()

    def quantize_weights(self, block=None):
        """Swap the stacked expert weights (in place) for their
        weight-only int8 serving form: each ``[E, K, N]`` Parameter
        becomes an int8 buffer of the same shape plus an
        ``[E, ceil(K/B), N]`` f32 scale buffer (``<name>_scale``), and
        the grouped FFN reroutes through ``grouped_gemm_q8`` (in-VMEM
        dequant). Serving-side only — the quantized weights are frozen
        (see :mod:`paddle_tpu.quant`). The router gate stays float
        (tiny, and routing decisions are the quality-critical bits)."""
        from ..quant.format import effective_block, quantize_weight

        if self.weight_block:
            return
        # one nominal block; per-tensor effective blocks (clamped to
        # each K) are derived from it at build time
        block = effective_block(max(self.d_model, self.d_ff), block)
        for name in ("gate_proj", "up_proj", "down_proj"):
            p = getattr(self, name)
            b = min(block, p.shape[-2])
            q, s = quantize_weight(p, b)
            delattr(self, name)
            self.register_buffer(name, Tensor(np.asarray(q)))
            self.register_buffer(name + "_scale", Tensor(np.asarray(s)))
        self.weight_block = int(block)
        self._fns.clear()

    def to(self, device=None, dtype=None, blocking=None):
        # model-wide dtype casts must keep the quantized format's
        # invariant: scale sidecars stay f32 (bf16 scales would change
        # the dequant products; see quant.layers.WeightOnlyLinear.to)
        out = super().to(device=device, dtype=dtype, blocking=blocking)
        if self.weight_block:
            for name in ("gate_proj_scale", "up_proj_scale",
                         "down_proj_scale"):
                s = self._buffers[name]
                if s._data.dtype != jnp.float32:
                    s._data = s._data.astype(jnp.float32)
        return out

    def _build_fn(self, n):
        from ..incubate.moe import top_k_routing
        from ..ops.grouped_gemm import _grouped

        e, k = self.num_experts, self.top_k
        uk = False if self.sharded else None

        if self.weight_block:
            return self._build_q8_fn(n, e, k, uk)

        def fn(x2d, gate, wg, wu, wd):
            logits = jnp.matmul(x2d.astype(jnp.float32), gate)
            # dropless: capacity = n (an expert appears at most once in
            # any token's top-k, so its load never exceeds the token
            # count) — keep is all-True, nothing is ever dropped. The
            # price of that exactness is the strided [E*n, ...] buffer
            # (only n*k rows real; the kernel skips the rest's MXU
            # work): fine at serving chunk budgets, and the lever to
            # revisit if E*chunk_budget ever dominates HBM.
            slot_token, expert_of, pos_of, keep, weights, aux = \
                top_k_routing(logits, k, n, normalize=True)
            gs = jnp.zeros((e,), jnp.int32).at[expert_of.reshape(-1)] \
                .add(keep.reshape(-1).astype(jnp.int32))
            gathered = x2d[jnp.maximum(slot_token, 0)]      # [E*n, D]
            g = _grouped(gathered, wg, gs, use_kernel=uk)
            u = _grouped(gathered, wu, gs, use_kernel=uk)
            h = jax.nn.silu(g) * u                          # swiglu
            y = _grouped(h, wd, gs, use_kernel=uk)
            idx = expert_of * n + jnp.clip(pos_of, 0, n - 1)
            picked = y[idx]                                 # [n, k, D]
            wk = (weights * keep).astype(x2d.dtype)
            return jnp.einsum("nk,nkd->nd", wk, picked), aux

        return fn

    def _build_q8_fn(self, n, e, k, uk):
        """The weight-only int8 forward: same routing, the three
        grouped GEMMs ride ``grouped_gemm_q8`` (int8 expert weights +
        scale sidecars, in-VMEM dequant). Per-tensor effective blocks
        clamp the nominal block to each contraction dim."""
        from ..incubate.moe import top_k_routing
        from ..ops.grouped_gemm import _grouped_q8

        bg = min(self.weight_block, self.d_model)   # gate/up: K=d_model
        bd = min(self.weight_block, self.d_ff)      # down: K=d_ff

        def fn(x2d, gate, wg, sg, wu, su, wd, sd):
            logits = jnp.matmul(x2d.astype(jnp.float32), gate)
            slot_token, expert_of, pos_of, keep, weights, aux = \
                top_k_routing(logits, k, n, normalize=True)
            gs = jnp.zeros((e,), jnp.int32).at[expert_of.reshape(-1)] \
                .add(keep.reshape(-1).astype(jnp.int32))
            gathered = x2d[jnp.maximum(slot_token, 0)]      # [E*n, D]
            g = _grouped_q8(gathered, wg, sg, gs, bg, use_kernel=uk)
            u = _grouped_q8(gathered, wu, su, gs, bg, use_kernel=uk)
            h = jax.nn.silu(g) * u                          # swiglu
            y = _grouped_q8(h, wd, sd, gs, bd, use_kernel=uk)
            idx = expert_of * n + jnp.clip(pos_of, 0, n - 1)
            picked = y[idx]                                 # [n, k, D]
            wk = (weights * keep).astype(x2d.dtype)
            return jnp.einsum("nk,nkd->nd", wk, picked), aux

        return fn

    def build_fn(self, n_tokens):
        """Public access to the per-token-count compiled forward
        (``fn(x2d, gate, gate_proj, up_proj, down_proj) -> (out,
        aux)`` on raw arrays), compile-watched as ``moe_mlp`` with a
        bounded LRU cache."""
        from ..incubate.moe import _watched_fn_cache

        return _watched_fn_cache(self._fns, int(n_tokens),
                                 self._build_fn, "moe_mlp",
                                 self.FN_CACHE_SIZE)

    def forward(self, x):
        from ..framework.tensor import run_op

        shape = x.shape
        d = shape[-1]
        n = 1
        for s in shape[:-1]:
            n *= s
        x2d = x.reshape([n, d])
        if self.weight_block:
            # frozen int8 weights: the op is not differentiable
            out, aux = run_op(
                "moe_mlp", self.build_fn(n),
                (x2d, self.gate, self.gate_proj, self.gate_proj_scale,
                 self.up_proj, self.up_proj_scale, self.down_proj,
                 self.down_proj_scale), differentiable=False)
        else:
            out, aux = run_op(
                "moe_mlp", self.build_fn(n),
                (x2d, self.gate, self.gate_proj, self.up_proj,
                 self.down_proj))
        self.l_aux = aux
        return out.reshape(shape)


class LlamaAttention(nn.Layer):
    """GQA attention with rotary embeddings; [B, S, H, D] layout throughout
    so the Pallas flash kernel path needs no relayout."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.head_dim
        h, hk, d = self.num_heads, self.num_kv_heads, self.head_dim
        wa = _winit(config)
        self.q_proj = nn.Linear(config.hidden_size, h * d, weight_attr=wa,
                                bias_attr=False)
        self.k_proj = nn.Linear(config.hidden_size, hk * d, weight_attr=wa,
                                bias_attr=False)
        self.v_proj = nn.Linear(config.hidden_size, hk * d, weight_attr=wa,
                                bias_attr=False)
        self.o_proj = nn.Linear(h * d, config.hidden_size, weight_attr=wa,
                                bias_attr=False)

    def forward(self, x, position_ids=None, cache=None, cache_len=None,
                attn_mask=None):
        b, s = x.shape[0], x.shape[1]
        h, hk, d = self.num_heads, self.num_kv_heads, self.head_dim
        q = self.q_proj(x).reshape([b, s, h, d])
        k = self.k_proj(x).reshape([b, s, hk, d])
        v = self.v_proj(x).reshape([b, s, hk, d])
        if cache is not None and cache_len is None:
            raise ValueError(
                "cache_len (scalar int Tensor) is required when a KV "
                "cache is passed — the static buffer needs the write "
                "offset")
        if position_ids is None and cache is not None:
            # direct layer use: rope continues after the cached prefix
            # (LlamaModel.forward precomputes this; keep the layer correct
            # standalone too)
            from ..tensor import creation
            position_ids = creation.arange(
                0, s, dtype="int64").reshape([1, s]) \
                + cache_len.astype("int64")
        q, k, v = FI.fused_rotary_position_embedding(
            q, k, v, position_ids=position_ids,
            rotary_emb_base=self.config.rope_theta)
        if cache is not None:
            # decode path: write into the static [B, max_len, Hk, D] buffer
            # at cache_len (the TPU idiom — no shape growth, one compile for
            # all decode steps; reference capability:
            # phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu)
            k_buf = _kv_cache_update(cache[0], k, cache_len)
            v_buf = _kv_cache_update(cache[1], v, cache_len)
            if attn_mask is None:
                attn_mask = _decode_mask(cache_len, s, k_buf.shape[1])
            out = F.scaled_dot_product_attention(q, k_buf, v_buf,
                                                 attn_mask=attn_mask)
            out = self.o_proj(out.reshape([b, s, h * d]))
            return out, (k_buf, v_buf)
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        return self.o_proj(out.reshape([b, s, h * d]))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(
            config.hidden_size, epsilon=config.rms_norm_eps)
        # config-selected FFN: the serving engine's mixed program and
        # the plain forward both call self.mlp, so an MoE checkpoint
        # serves with zero scheduler changes
        self.mlp = LlamaMoEMLP(config) if config.moe_num_experts \
            else LlamaMLP(config)

    def forward(self, x, position_ids=None, cache=None, cache_len=None,
                attn_mask=None):
        h = self.input_layernorm(x)
        if cache is not None:
            attn, cache = self.self_attn(h, position_ids, cache, cache_len,
                                         attn_mask)
        else:
            attn = self.self_attn(h, position_ids)
        x = x + attn
        x = x + self.mlp(self.post_attention_layernorm(x))
        if cache is not None:
            return x, cache
        return x


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size,
                                         weight_attr=_winit(config))
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size,
                               epsilon=config.rms_norm_eps)

    def forward(self, input_ids, position_ids=None, caches=None,
                cache_len=None):
        x = self.embed_tokens(input_ids)
        new_caches = [] if caches is not None else None
        attn_mask = None
        if caches is not None:
            if cache_len is None:
                raise ValueError(
                    "cache_len is required when caches are passed")
            s = input_ids.shape[1]
            if position_ids is None:
                # rope positions continue after the cached prefix
                # (cache_len is a traced scalar: one program per shape)
                from ..tensor import creation
                position_ids = creation.arange(
                    0, s, dtype="int64").reshape([1, s]) \
                    + cache_len.astype("int64")
            # identical for every layer — build once, not per layer
            attn_mask = _decode_mask(cache_len, s, caches[0][0].shape[1])
        use_remat = self.config.recompute and caches is None \
            and not x.stop_gradient
        for i, layer in enumerate(self.layers):
            if caches is not None:
                x, c = layer(x, position_ids, caches[i], cache_len,
                             attn_mask)
                new_caches.append(c)
            elif use_remat:
                from ..distributed.recompute import recompute
                pol = "dots" if self.config.recompute == "dots" else None
                x = recompute(layer, x, position_ids, policy=pol)
            else:
                x = layer(x, position_ids)
        x = self.norm(x)
        if caches is not None:
            return x, new_caches
        return x


class LlamaForCausalLM(nn.Layer):
    """Decoder LM. ``forward(input_ids, labels=None)`` returns logits;
    with next-token labels (the input shifted by the caller,
    ignore_index=-100) it returns ``(loss, None)`` on the default
    chunked fused cross-entropy path — the logits are never built — or
    ``(loss, logits)`` under ``PADDLE_TPU_FUSED_CE=0`` / tied
    embeddings (the materialized path)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     weight_attr=_winit(config),
                                     bias_attr=False)

    def _logits(self, hidden):
        if self.lm_head is not None:
            return self.lm_head(hidden)
        from ..tensor import linalg
        return linalg.matmul(hidden, self.model.embed_tokens.weight,
                             transpose_y=True)

    def _fused_ce_enabled(self):
        """Default loss path: the chunked fused cross-entropy lm-head
        (``ops.fused_linear_cross_entropy``) — the ``[B*S, V]`` logits
        tensor never exists. ``PADDLE_TPU_FUSED_CE=0`` restores the
        materialized path byte-for-byte (and the tied-embedding model,
        whose projection is the transposed embedding table, always
        takes it)."""
        import os
        if self.lm_head is None:
            return False
        return os.environ.get("PADDLE_TPU_FUSED_CE", "1") != "0"

    def forward(self, input_ids, labels=None, position_ids=None):
        hidden = self.model(input_ids, position_ids)
        if labels is not None and self._fused_ce_enabled():
            # fused path returns (loss, None): logits were never built.
            # Callers needing them set PADDLE_TPU_FUSED_CE=0.
            from ..ops.fused_linear_cross_entropy import (
                fused_linear_cross_entropy)
            loss = fused_linear_cross_entropy(
                hidden, self.lm_head.weight, labels, ignore_index=-100)
            return loss, None
        logits = self._logits(hidden)
        if labels is None:
            return logits
        v = self.config.vocab_size
        loss = F.cross_entropy(
            logits.reshape([-1, v]).astype("float32"),
            labels.reshape([-1]), ignore_index=-100)
        return loss, logits

    def num_params(self):
        return sum(int(np.prod(p.shape)) for p in self.parameters())

    def flops_per_token(self, seq_len):
        """Approximate training FLOPs/token: 6*N_matmul_params + attention
        term (the standard MFU accounting). The embedding lookup is a
        gather, not a matmul, so its params are excluded — unless the
        embedding is tied and doubles as the output projection."""
        cfg = self.config
        n = self.num_params()
        if not cfg.tie_word_embeddings:
            n -= cfg.vocab_size * cfg.hidden_size  # embed_tokens lookup
        attn = 12 * cfg.num_hidden_layers * cfg.hidden_size * seq_len
        return 6 * n + attn

    @staticmethod
    def _pick_token(logits, rng_key, sampler):
        """next-token rule on [B, 1, V] logits. ``sampler`` is a static
        (do_sample, top_k, top_p, temperature) tuple — each distinct
        config compiles its own decode program."""
        from ..framework.tensor import run_op
        from ..tensor import search

        do_sample, top_k, top_p, temperature = sampler
        if not do_sample:
            return search.argmax(logits, axis=-1).astype("int64")

        def fn(logits, key):
            lg = logits[:, 0, :].astype(jnp.float32)
            lg = lg / max(float(temperature), 1e-6)
            if top_k:  # None or 0 disables the filter (HF/paddle convention)
                k = min(int(top_k), lg.shape[-1])
                kth = jnp.sort(lg, axis=-1)[:, -k][:, None]
                lg = jnp.where(lg >= kth, lg, -1e30)
            if top_p is not None:
                # nucleus over the (possibly top-k-restricted) softmax
                probs = jax.nn.softmax(lg, axis=-1)
                order = jnp.argsort(-probs, axis=-1)
                sp = jnp.take_along_axis(probs, order, axis=-1)
                cum_before = jnp.cumsum(sp, axis=-1) - sp
                keep_sorted = cum_before < float(top_p)
                keep = jnp.zeros_like(keep_sorted).at[
                    jnp.arange(lg.shape[0])[:, None], order].set(
                    keep_sorted)
                lg = jnp.where(keep, lg, -1e30)
            return jax.random.categorical(key, lg, axis=-1)[:, None]

        return run_op("sample_next_token", fn, (logits, rng_key),
                      differentiable=False).astype("int64")

    def _decode_step(self, tokens, cache_len, caches, rng_key=None,
                     sampler=(False, None, None, 1.0)):
        """One generation step: (next_token, new_cache_len, new_caches).
        Pure in (tokens, cache_len, caches, rng_key) so ``to_static``
        compiles it ONCE per shape — the static KV buffers keep every
        decode step the same program, and with input donation XLA updates
        them in place."""
        hidden, caches = self.model(tokens, None, caches, cache_len)
        logits = self._logits(hidden[:, -1:])
        nxt = self._pick_token(logits, rng_key, sampler)
        new_len = cache_len + tokens.shape[1]
        return nxt, new_len, caches

    def generate(self, input_ids, max_new_tokens=16, max_length=None,
                 do_sample=False, top_k=None, top_p=None, temperature=1.0,
                 seed=None):
        """Decode over a static KV cache: one compile for the prefill
        shape + one for the single-token decode shape, reused for every
        subsequent step and every same-shape call. Greedy by default;
        ``do_sample=True`` samples inside the compiled step (temperature
        -> top-k -> top-p nucleus -> categorical), deterministic under
        ``seed``. Inputs of the compiled step are donated (the caches
        alias in place on device), so nothing passed to one step is
        touched after it. The buffer length is bucketed (multiple of 64)
        so prompts of different lengths share the same decode executable."""
        from ..framework.tensor import Tensor, no_grad
        from ..framework import random as frandom
        from ..tensor import manipulation as M
        from .. import jit
        import jax.numpy as jnp

        sampler = (bool(do_sample), top_k, top_p, float(temperature))
        # the compiled step pins parameter objects + the sampler config;
        # rebuild if either changed (e.g. shard_llama swapped Parameters)
        param_key = (tuple(id(p) for p in self.parameters()), sampler)
        if getattr(self, "_decode_static", None) is None \
                or self._decode_param_key != param_key:
            def step_fn(tokens, cache_len, caches, rng_key):
                return self._decode_step(tokens, cache_len, caches,
                                         rng_key, sampler)
            # donate=False: weights are read-only pass-through in the
            # decode step, so donating them buys nothing — and with a
            # quantized model's many same-aval int8/scale slots XLA's
            # aval-based alias matching can scramble the pass-through
            # outputs across donated buffers (the caches still donate
            # via donate_inputs, which is where the in-place win lives)
            self._decode_static = jit.StaticFunction(
                step_fn, state=[self], warmup="once", donate=False,
                donate_inputs=True, name="llama.generate_step")
            self._decode_param_key = param_key
        step = self._decode_static
        base_key = jax.random.key(seed) if seed is not None \
            else frandom.next_key()
        with no_grad():
            b, s = input_ids.shape[0], input_ids.shape[1]
            need = s + max_new_tokens
            max_len = max_length if max_length is not None \
                else ((need + 63) // 64) * 64
            if max_len < need:
                raise ValueError(
                    f"max_length={max_len} < prompt + max_new_tokens "
                    f"({need})")
            caches = self._empty_caches(b, max_len)
            cache_len = Tensor(jnp.asarray(0, jnp.int32))
            # clone: the step donates its inputs, and the caller's
            # input_ids must survive
            tokens = Tensor(jnp.array(input_ids._data))
            new_tokens = []
            for i in range(max_new_tokens):
                key = Tensor(jax.random.fold_in(base_key, i))
                nxt, cache_len, caches = step(tokens, cache_len, caches,
                                              key)
                tokens = nxt.reshape([b, 1])
                # copy: `tokens` itself is donated into the next step, but
                # the appended value must survive until the final concat
                new_tokens.append(Tensor(jnp.array(tokens._data)))
            return M.concat([input_ids] + new_tokens, axis=1)

    def _empty_caches(self, batch, max_len):
        from ..tensor import creation
        cfg = self.config
        dt = self.model.embed_tokens.weight.dtype  # match model dtype
        return [
            (creation.zeros([batch, max_len, cfg.num_key_value_heads,
                             cfg.head_dim], dtype=dt),
             creation.zeros([batch, max_len, cfg.num_key_value_heads,
                             cfg.head_dim], dtype=dt))
            for _ in range(cfg.num_hidden_layers)]


# ---------------------------------------------------------------------------
# sharding recipe: (tp, fsdp) placements per weight — the Megatron layout
# expressed as GSPMD annotations (reference: fleet/layers/mpu/mp_layers.py)
# ---------------------------------------------------------------------------
def shard_llama(model: LlamaForCausalLM, mesh, tp_axis="mp",
                fsdp_axis=None, ep_axis=None):
    """Annotate a LlamaForCausalLM's weights over ``mesh``.

    - attention q/k/v and mlp gate/up: column-parallel (out-dim on tp)
    - attention o and mlp down: row-parallel (in-dim on tp)
    - embedding + lm_head: vocab-parallel
    - fsdp_axis (optional) shards the *other* matrix dim, giving the
      ZeRO-3 layout; norms shard on fsdp only.
    - ep_axis (optional, MoE models) shards the stacked ``[E, ...]``
      expert weights on their EXPERT dim over that mesh axis — expert
      parallelism: each rank owns ``E / ep`` experts' FFN weights, the
      router stays replicated (every rank routes every token), and the
      grouped-GEMM path demotes to the GSPMD XLA formulation exactly as
      the ``sharded`` stamp already does, so GSPMD partitions the
      batched per-expert dot and inserts the dispatch collectives.
    """
    from ..distributed import shard_tensor, Shard, Replicate

    tp_dim = mesh.dim_names.index(tp_axis) if tp_axis else None
    fs_dim = mesh.dim_names.index(fsdp_axis) if fsdp_axis else None
    ep_dim = mesh.dim_names.index(ep_axis) if ep_axis else None
    if ep_axis and not model.config.moe_num_experts:
        raise ValueError(
            "ep_axis shards stacked expert weights, but this config has "
            "moe_num_experts == 0 (dense FFN) — nothing to shard")

    def place(t, tp_tensor_dim, fsdp_tensor_dim, ep_tensor_dim=None):
        p = [Replicate()] * mesh.ndim
        if tp_dim is not None and tp_tensor_dim is not None:
            p[tp_dim] = Shard(tp_tensor_dim)
        if fs_dim is not None and fsdp_tensor_dim is not None:
            p[fs_dim] = Shard(fsdp_tensor_dim)
        if ep_dim is not None and ep_tensor_dim is not None:
            p[ep_dim] = Shard(ep_tensor_dim)
        return shard_tensor(t, mesh, p)

    m = model.model
    m.embed_tokens.weight = place(m.embed_tokens.weight, 0, 1)
    if model.lm_head is not None:
        model.lm_head.weight = place(model.lm_head.weight, 1, 0)
    for layer in m.layers:
        a, mlp = layer.self_attn, layer.mlp
        a.q_proj.weight = place(a.q_proj.weight, 1, 0)
        a.k_proj.weight = place(a.k_proj.weight, 1, 0)
        a.v_proj.weight = place(a.v_proj.weight, 1, 0)
        a.o_proj.weight = place(a.o_proj.weight, 0, 1)
        if isinstance(mlp, LlamaMoEMLP):
            # stacked [E, in, out] expert weights: tp splits the FFN
            # width exactly like the dense column/row layout; the
            # router stays replicated on tp AND ep (every rank routes
            # every token); fsdp shards the other matrix dim; ep shards
            # the expert dim itself
            mlp.gate = place(mlp.gate, None, 0)
            mlp.gate_proj = place(mlp.gate_proj, 2, 1, 0)
            mlp.up_proj = place(mlp.up_proj, 2, 1, 0)
            mlp.down_proj = place(mlp.down_proj, 1, 2, 0)
            # sharded experts: GSPMD needs the XLA grouped formulation
            # (drop any kernel-path programs built before sharding)
            mlp.sharded = True
            mlp._fns.clear()
        else:
            mlp.gate_proj.weight = place(mlp.gate_proj.weight, 1, 0)
            mlp.up_proj.weight = place(mlp.up_proj.weight, 1, 0)
            mlp.down_proj.weight = place(mlp.down_proj.weight, 0, 1)
        layer.input_layernorm.weight = place(
            layer.input_layernorm.weight, None, 0)
        layer.post_attention_layernorm.weight = place(
            layer.post_attention_layernorm.weight, None, 0)
    m.norm.weight = place(m.norm.weight, None, 0)
    return model
