"""``paddle_tpu.models`` — flagship model families.

The reference keeps its LLM recipes out-of-tree (PaddleNLP), but the
BASELINE north star is Llama-3-8B pretraining MFU, so the decoder family
lives in-tree here, built on the incubate fused ops + Pallas GQA flash
attention.
"""

from .llama import (  # noqa: F401
    LlamaConfig, LlamaMLP, LlamaMoEMLP, LlamaAttention, LlamaDecoderLayer, LlamaModel,
    LlamaForCausalLM, shard_llama, llama3_8b_config, tiny_llama_config,
)
from .llama_pipe import LlamaForCausalLMPipe  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForSequenceClassification,
    BertForTokenClassification, ErnieModel,
    ErnieForSequenceClassification, ernie_base_config, tiny_bert_config,
)

__all__ = [
    "LlamaConfig", "LlamaMLP", "LlamaMoEMLP", "LlamaAttention", "LlamaDecoderLayer",
    "LlamaModel", "LlamaForCausalLM", "shard_llama", "llama3_8b_config",
    "tiny_llama_config", "LlamaForCausalLMPipe",
    "BertConfig", "BertModel", "BertForSequenceClassification",
    "BertForTokenClassification", "ErnieModel",
    "ErnieForSequenceClassification", "ernie_base_config",
    "tiny_bert_config",
]
