"""Einsum (reference: `python/paddle/tensor/einsum.py` — here a direct
lowering to XLA's native einsum, which maps contractions onto the MXU)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import run_op

__all__ = ["einsum"]


def einsum(equation, *operands, name=None):
    return run_op("einsum",
                  lambda *xs: jnp.einsum(equation, *xs), list(operands))
