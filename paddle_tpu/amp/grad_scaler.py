"""Dynamic loss scaling.

Reference: `python/paddle/amp/grad_scaler.py:1` (``GradScaler``). bf16
training (the TPU default) does not need loss scaling — construct with
``enable=False`` or just skip the scaler; this class exists for float16
parity and for the API surface (`scale`/`unscale_`/`step`/`update`/
``minimize``).

Trace-compilation note: under ``jit.to_static`` the overflow check is a
traced value, so a Python ``if`` cannot skip the step. The traced path
instead masks the update — gradients and the learning rate are multiplied
by ``0`` on overflow, leaving parameters (and decoupled weight decay)
bit-exact unchanged; only optimizer moments decay toward zero on the
skipped step, a documented deviation from the reference's hard skip. The
scaler's own state (scale, growth tracker) updates with ``jnp.where`` so
it stays inside the compiled program (expose it to ``to_static`` state
discovery via ``__state_tensors__``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..observability import metrics as _om

__all__ = ["GradScaler", "AmpScaler"]


def _is_traced(x):
    return isinstance(x, jax.core.Tracer)


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = bool(enable)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every_n_steps = int(incr_every_n_steps)
        self._decr_every_n_nan_or_inf = int(decr_every_n_nan_or_inf)
        self._use_dynamic = bool(use_dynamic_loss_scaling)
        self._scale = Tensor(jnp.asarray(init_loss_scaling, jnp.float32))
        self._growth = Tensor(jnp.asarray(0, jnp.int32))
        self._bad = Tensor(jnp.asarray(0, jnp.int32))
        self._found_inf = None        # set by unscale_
        self._unscaled = set()        # optimizers already unscaled this step
        # counters observe only on the eager path; under to_static the
        # overflow flag is a tracer and cannot be read host-side
        self._m_found_inf = _om.counter(
            "amp_found_inf_total", "steps with non-finite gradients")
        self._m_backoff = _om.counter(
            "amp_scale_backoff_total", "loss-scale decreases")

    # -- to_static integration ---------------------------------------------
    def __state_tensors__(self):
        return [self._scale, self._growth, self._bad]

    # -- API ----------------------------------------------------------------
    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._enable and self._use_dynamic

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        # in-place payload update: to_static state discovery holds these
        # Tensor objects by identity, rebinding would silently fork state
        self._scale._data = jnp.asarray(v, jnp.float32)

    def scale(self, var):
        """Multiply the loss by the current scale (recorded on the tape)."""
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        """Divide accumulated grads by the scale; record overflow status."""
        if not self._enable:
            return
        inv = 1.0 / self._scale._data
        found = jnp.asarray(False)
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._data * inv.astype(p.grad._data.dtype)
            found = jnp.logical_or(found, ~jnp.isfinite(g).all())
            p.grad._data = g
        self._found_inf = found
        self._unscaled.add(id(optimizer))

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if id(optimizer) not in self._unscaled:
            self.unscale_(optimizer)
        found = self._found_inf
        if not _is_traced(found):
            if not bool(found):
                optimizer.step()
            self._unscaled.discard(id(optimizer))
            return
        # traced: mask grads + lr so an overflow step leaves params intact.
        # select-with-where, NOT multiply — inf * 0 is NaN and would poison
        # the update the mask exists to suppress
        ok = 1.0 - found.astype(jnp.float32)
        for p in optimizer._parameter_list:
            if p.grad is not None:
                g = p.grad._data
                p.grad._data = jnp.where(found, jnp.zeros_like(g), g)
        prev = optimizer._lr_override
        base = prev if prev is not None else optimizer.get_lr()
        optimizer._lr_override = base * ok
        try:
            optimizer.step()
        finally:
            optimizer._lr_override = prev
        self._unscaled.discard(id(optimizer))

    def update(self):
        """Dynamic loss-scale bookkeeping (traceable)."""
        if not (self._enable and self._use_dynamic):
            return
        found = self._found_inf
        if found is None:
            return
        found_i = jnp.asarray(found).astype(jnp.int32)
        bad = self._bad._data + found_i
        growth = jnp.where(found_i > 0, 0, self._growth._data + 1)
        shrink = bad >= self._decr_every_n_nan_or_inf
        grow = growth >= self._incr_every_n_steps
        if self._m_found_inf is not _om.NULL and not _is_traced(shrink):
            # one batched D2H for both flags; skipped entirely when the
            # counters are the shared no-op (PADDLE_TPU_METRICS=0)
            found_host, shrink_host = jax.device_get(
                [found_i > 0, shrink])
            if found_host:
                self._m_found_inf.inc()
            if shrink_host:
                self._m_backoff.inc()
        scale = self._scale._data
        scale = jnp.where(shrink, scale * self._decr_ratio, scale)
        scale = jnp.where(grow, scale * self._incr_ratio, scale)
        self._scale._data = jnp.maximum(scale, 1.0)
        self._growth._data = jnp.where(grow, 0, growth)
        self._bad._data = jnp.where(shrink, 0, bad)
        self._found_inf = None

    def minimize(self, optimizer, scaled_loss=None):
        """unscale -> (maybe) step -> update, the reference's one-shot."""
        self.step(optimizer)
        self.update()

    # -- state dict ---------------------------------------------------------
    def state_dict(self):
        if not self._enable:
            return {}
        return {
            "scale": self._scale.numpy(),
            "incr_ratio": self._incr_ratio,
            "decr_ratio": self._decr_ratio,
            "incr_every_n_steps": self._incr_every_n_steps,
            "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
            "growth": self._growth.numpy(),
            "bad": self._bad.numpy(),
            "use_dynamic_loss_scaling": self._use_dynamic,
        }

    def load_state_dict(self, state):
        if not state:
            return
        # in-place: see set_init_loss_scaling
        self._scale._data = jnp.asarray(state["scale"], jnp.float32)
        self._growth._data = jnp.asarray(state.get("growth", 0), jnp.int32)
        self._bad._data = jnp.asarray(state.get("bad", 0), jnp.int32)
        self._incr_ratio = float(state.get("incr_ratio", self._incr_ratio))
        self._decr_ratio = float(state.get("decr_ratio", self._decr_ratio))
        self._incr_every_n_steps = int(
            state.get("incr_every_n_steps", self._incr_every_n_steps))
        self._decr_every_n_nan_or_inf = int(
            state.get("decr_every_n_nan_or_inf",
                      self._decr_every_n_nan_or_inf))
        self._use_dynamic = bool(
            state.get("use_dynamic_loss_scaling", self._use_dynamic))


AmpScaler = GradScaler  # legacy alias (reference: base/dygraph/amp/loss_scaler)
