"""AMP debugging tools (reference: `python/paddle/amp/debugging.py` —
operator stats collection, tensor checking, accuracy comparison).

- ``collect_operator_stats``: context manager counting op executions by
  dtype through a ``run_op`` observer (the reference instruments the
  generated eager ops), printed as the reference's four-column table.
- ``enable_tensor_checker``/``disable_tensor_checker``: the
  ``FLAGS_check_nan_inf`` switch (the reference's debug-mode checker).
- ``check_numerics``: count nan/inf in one tensor.
- ``compare_accuracy``: run a function under two dtypes and report
  per-output max abs/rel error (the reference's excel workflow, as a
  returned dict instead of a spreadsheet).
"""

from __future__ import annotations

import collections
import contextlib

import jax.numpy as jnp
import numpy as np

from .. import flags
from ..framework import tensor as _tensor_mod

__all__ = ["collect_operator_stats", "enable_operator_stats_collection",
           "disable_operator_stats_collection", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics", "compare_accuracy"]

_op_stats = None


def _observer(name, out):
    if _op_stats is None:
        return
    outs = out if isinstance(out, (tuple, list)) else (out,)
    for o in outs:
        dt = str(getattr(o, "dtype", "other"))
        if "float16" in dt and "b" not in dt:
            col = "fp16"
        elif "bfloat16" in dt:
            col = "bf16"
        elif "float32" in dt:
            col = "fp32"
        else:
            col = "other"
        _op_stats[name][col] += 1


def enable_operator_stats_collection():
    """Start counting op calls by output dtype (reference
    `debugging.py:enable_operator_stats_collection`)."""
    global _op_stats
    _op_stats = collections.defaultdict(
        lambda: {"fp16": 0, "bf16": 0, "fp32": 0, "other": 0})
    _tensor_mod.op_observers.append(_observer)


def disable_operator_stats_collection():
    """Stop collecting and print the dtype table."""
    global _op_stats
    if _op_stats is None:
        return {}
    try:
        _tensor_mod.op_observers.remove(_observer)
    except ValueError:
        pass
    stats, _op_stats = dict(_op_stats), None
    w = max([len(k) for k in stats] + [8])
    print("<------------------------------ op list "
          "------------------------------->")
    print(f"{'op':<{w}}  {'fp16':>6} {'bf16':>6} {'fp32':>6} {'other':>6}")
    for name in sorted(stats):
        s = stats[name]
        print(f"{name:<{w}}  {s['fp16']:>6} {s['bf16']:>6} {s['fp32']:>6} "
              f"{s['other']:>6}")
    print("<----------------------------------- end "
          "---------------------------------->")
    return stats


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def enable_tensor_checker():
    """nan/inf checking on every op output (reference debug mode —
    here the FLAGS_check_nan_inf hook in run_op)."""
    flags.set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    flags.set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor, op_name="", var_name=""):
    """Returns (num_nan, num_inf) as int tensors-like values. A hit
    prints the reference-style line, increments
    ``paddle_tpu_nan_inf_detected_total{op,var}``, and triggers the
    crash flight recorder when one is installed (a NaN blow-up is
    exactly the moment the recent-spans/compiles/metrics ring matters)."""
    arr = np.asarray(getattr(tensor, "_data", tensor), np.float64)
    n_nan = int(np.isnan(arr).sum())
    n_inf = int(np.isinf(arr).sum())
    if n_nan or n_inf:
        print(f"[check_numerics] op={op_name} var={var_name} "
              f"num_nan={n_nan} num_inf={n_inf}")
        from ..observability import flight_recorder as _fr
        from ..observability import metrics as _om
        _om.counter("paddle_tpu_nan_inf_detected_total",
                    "non-finite values caught by check_numerics",
                    labelnames=("op", "var")) \
            .labels(op_name or "(unknown)", var_name or "(unknown)").inc()
        _fr.on_fatal("check_numerics", op=op_name, var=var_name,
                     num_nan=n_nan, num_inf=n_inf)
    return n_nan, n_inf


def compare_accuracy(fn, args, dtypes=("float32", "bfloat16"), atol=None):
    """Run ``fn(*args)`` once per dtype (inputs cast) and report
    per-output max-abs / max-rel deltas vs the first dtype."""
    from ..framework.tensor import Tensor

    def cast_all(dt):
        out = []
        for a in args:
            if isinstance(a, Tensor) and jnp.issubdtype(
                    a._data.dtype, jnp.floating):
                out.append(a.astype(dt))
            else:
                out.append(a)
        return out

    results = {}
    for dt in dtypes:
        r = fn(*cast_all(dt))
        results[dt] = [np.asarray(o._data, np.float64)
                       for o in (r if isinstance(r, (tuple, list))
                                 else (r,))]
    base = results[dtypes[0]]
    report = {}
    for dt in dtypes[1:]:
        per_out = []
        for a, b in zip(base, results[dt]):
            diff = np.abs(a - b)
            per_out.append({
                "max_abs_err": float(diff.max()) if diff.size else 0.0,
                "max_rel_err": float(
                    (diff / (np.abs(a) + 1e-12)).max()) if diff.size
                else 0.0,
            })
        report[dt] = per_out
    return report
