"""BERT/ERNIE-style encoder family (the BASELINE.md transformer-encoder
path: "ERNIE-3.0-base finetune functional parity").

Reference capability: the reference trains ERNIE via PaddleNLP on its
`nn.TransformerEncoder` (`python/paddle/nn/layer/transformer.py`) —
this module is the in-tree TPU-native recipe on the same layers:
embeddings (word + position + token type) -> pre/post-LN encoder stack ->
pooler, with task heads for sequence and token classification.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from .. import nn
from ..nn import functional as F
from ..nn.initializer import Normal

__all__ = ["BertConfig", "BertModel", "BertForSequenceClassification",
           "BertForTokenClassification", "ErnieModel",
           "ErnieForSequenceClassification", "ernie_base_config",
           "tiny_bert_config"]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12


def ernie_base_config():
    """ERNIE-3.0-base shape (12L, 768H, 12 heads)."""
    return BertConfig(vocab_size=40000, max_position_embeddings=2048,
                      type_vocab_size=4)


def tiny_bert_config(**kw):
    base = dict(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                num_attention_heads=4, intermediate_size=64,
                max_position_embeddings=64, type_vocab_size=2,
                hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    base.update(kw)
    return BertConfig(**base)


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        wa = Normal(std=cfg.initializer_range)
        self.word_embeddings = nn.Embedding(cfg.vocab_size,
                                            cfg.hidden_size, weight_attr=wa)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size, weight_attr=wa)
        self.token_type_embeddings = nn.Embedding(
            cfg.type_vocab_size, cfg.hidden_size, weight_attr=wa)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from ..tensor import creation
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = creation.arange(0, s, dtype="int64") \
                .reshape([1, s])
        if token_type_ids is None:
            token_type_ids = creation.zeros_like(input_ids)
        x = self.word_embeddings(input_ids) \
            + self.position_embeddings(position_ids) \
            + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertModel(nn.Layer):
    """Embeddings -> TransformerEncoder -> (sequence_output, pooled)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
            act_dropout=0.0, normalize_before=False,
            layer_norm_eps=config.layer_norm_eps)
        self.encoder = nn.TransformerEncoder(layer,
                                             config.num_hidden_layers)
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] 1/0 padding mask -> additive [B, 1, 1, S]
            m = (1.0 - attention_mask.astype("float32")) * -1e4
            attention_mask = m.reshape(
                [attention_mask.shape[0], 1, 1, attention_mask.shape[1]])
        seq = self.encoder(x, src_mask=attention_mask)
        pooled = F.tanh(self.pooler(seq[:, 0]))
        return seq, pooled


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        loss = F.cross_entropy(logits.astype("float32"),
                               labels.reshape([-1]))
        return loss, logits


class BertForTokenClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes=2):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        seq, _ = self.bert(input_ids, token_type_ids, position_ids,
                           attention_mask)
        logits = self.classifier(self.dropout(seq))
        if labels is None:
            return logits
        n = logits.shape[-1]
        loss = F.cross_entropy(
            logits.reshape([-1, n]).astype("float32"),
            labels.reshape([-1]), ignore_index=-100)
        return loss, logits


# ERNIE shares the architecture; the difference is pretraining data/task
ErnieModel = BertModel
ErnieForSequenceClassification = BertForSequenceClassification
