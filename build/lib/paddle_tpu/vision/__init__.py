"""``paddle_tpu.vision`` — datasets, transforms, model zoo.

Reference: `python/paddle/vision/__init__.py`.
"""

from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401

__all__ = ["datasets", "models", "transforms", "ops"]
