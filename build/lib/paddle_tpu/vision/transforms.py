"""Vision transforms (numpy host-side preprocessing).

Reference: `python/paddle/vision/transforms/transforms.py`. These run on
the host inside DataLoader workers; the device only ever sees the final
batched array (one H2D per step).
"""

from __future__ import annotations

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomCrop", "RandomHorizontalFlip", "Transpose",
           "RandomResizedCrop", "RandomVerticalFlip", "ColorJitter",
           "Pad", "Grayscale", "RandomRotation", "RandomErasing"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor:
    """HWC uint8/float -> CHW float32 scaled to [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        raw = np.asarray(img)
        arr = raw.astype(np.float32)
        if raw.dtype == np.uint8:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[..., None]
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32)
        if self.data_format == "CHW":
            shape = (-1, 1, 1)
        else:
            shape = (1, 1, -1)
        return (arr - self.mean.reshape(shape)) / self.std.reshape(shape)


def _resize_nn(arr, size):
    """Nearest-neighbor resize (no cv2/PIL dependency)."""
    h, w = arr.shape[:2]
    nh, nw = size
    ri = (np.arange(nh) * h / nh).astype(np.int64)
    ci = (np.arange(nw) * w / nw).astype(np.int64)
    return arr[ri][:, ci]


class Resize:
    def __init__(self, size, interpolation="nearest"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        return _resize_nn(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            pad = [(self.padding, self.padding), (self.padding, self.padding)]
            pad += [(0, 0)] * (arr.ndim - 2)
            arr = np.pad(arr, pad)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.transpose(np.asarray(img), self.order)


class RandomResizedCrop:
    """Random area+aspect crop then resize (reference
    `vision/transforms/transforms.py:RandomResizedCrop`). HWC arrays,
    like the other transforms here."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round((target * ar) ** 0.5))
            ch = int(round((target / ar) ** 0.5))
            if 0 < cw <= w and 0 < ch <= h:
                y = np.random.randint(0, h - ch + 1)
                x = np.random.randint(0, w - cw + 1)
                return _resize_nn(arr[y:y + ch, x:x + cw], self.size)
        # fallback: center crop of the smaller side
        s = min(h, w)
        y, x = (h - s) // 2, (w - s) // 2
        return _resize_nn(arr[y:y + s, x:x + s], self.size)


class RandomVerticalFlip:
    """Reference RandomVerticalFlip (HWC)."""

    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        arr = np.asarray(img)
        if np.random.random() < self.prob:
            return arr[::-1].copy()
        return arr


class ColorJitter:
    """Brightness/contrast jitter on HWC float arrays (reference
    ColorJitter; hue/saturation need HSV — brightness/contrast cover the
    common training recipes)."""

    def __init__(self, brightness=0.0, contrast=0.0, saturation=0.0,
                 hue=0.0):
        if saturation or hue:
            raise NotImplementedError(
                "saturation/hue jitter not supported (needs HSV space); "
                "use brightness/contrast")
        self.brightness = brightness
        self.contrast = contrast

    def __call__(self, img):
        out = np.asarray(img)
        if self.brightness:
            f = np.random.uniform(max(0, 1 - self.brightness),
                                  1 + self.brightness)
            out = out * f
        if self.contrast:
            f = np.random.uniform(max(0, 1 - self.contrast),
                                  1 + self.contrast)
            out = (out - out.mean()) * f + out.mean()
        return out


class Pad:
    """Constant-pad H and W of an HWC array (reference transforms.Pad)."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, int):
            padding = (padding, padding, padding, padding)  # l, t, r, b
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        l, t, r, b = self.padding
        pad = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
        if self.padding_mode == "constant":
            return np.pad(arr, pad, constant_values=self.fill)
        return np.pad(arr, pad, mode=self.padding_mode)


class Grayscale:
    """ITU-R 601-2 luma transform on HWC RGB (reference
    transforms.Grayscale); num_output_channels 1 or 3."""

    def __init__(self, num_output_channels=1):
        if num_output_channels not in (1, 3):
            raise ValueError("num_output_channels must be 1 or 3")
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        arr = np.asarray(img)
        gray = (0.299 * arr[..., 0] + 0.587 * arr[..., 1]
                + 0.114 * arr[..., 2])[..., None]
        if self.num_output_channels == 3:
            gray = np.repeat(gray, 3, axis=-1)
        return gray.astype(arr.dtype)


class RandomRotation:
    """Rotate by a uniform random angle (reference
    transforms.RandomRotation); nearest-neighbor resample around the
    image center, out-of-frame pixels filled with ``fill``."""

    def __init__(self, degrees, fill=0):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.fill = fill

    def __call__(self, img):
        arr = np.asarray(img)
        angle = np.random.uniform(*self.degrees) * np.pi / 180.0
        h, w = arr.shape[:2]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float64)
        c, s = np.cos(angle), np.sin(angle)
        # inverse map: output pixel pulls from rotated source coordinate
        sy = cy + (yy - cy) * c - (xx - cx) * s
        sx = cx + (yy - cy) * s + (xx - cx) * c
        syi = np.round(sy).astype(np.int64)
        sxi = np.round(sx).astype(np.int64)
        valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
        out = np.full_like(arr, self.fill)
        out[valid] = arr[syi[valid], sxi[valid]]
        return out


class RandomErasing:
    """Erase a random rectangle (reference transforms.RandomErasing):
    area in ``scale`` x image, aspect in ``ratio``; value 0 or 'random'."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img).copy()
        if np.random.random() >= self.prob:
            return arr
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round((target / ar) ** 0.5))
            ew = int(round((target * ar) ** 0.5))
            if eh < h and ew < w and eh > 0 and ew > 0:
                y = np.random.randint(0, h - eh + 1)
                x = np.random.randint(0, w - ew + 1)
                if self.value == "random":
                    arr[y:y + eh, x:x + ew] = np.random.rand(
                        eh, ew, *arr.shape[2:]).astype(arr.dtype)
                else:
                    arr[y:y + eh, x:x + ew] = self.value
                return arr
        return arr
