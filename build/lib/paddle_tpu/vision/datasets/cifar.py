"""CIFAR datasets (reference: `python/paddle/vision/datasets/cifar.py`).

Parses the real ``cifar-10-python.tar.gz`` / ``cifar-100-python.tar.gz``
archives (pickled batches of [N, 3072] uint8 rows) when ``data_file`` is
given. With no archive (this build has zero egress) it falls back to a
deterministic synthetic task: each class is a distinct 32x32 RGB
frequency pattern plus noise — a real N-way classification problem for
end-to-end tests, clearly labeled as synthetic.
"""

from __future__ import annotations

import pickle
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Cifar10", "Cifar100"]


def _synthetic(mode, num_classes, n_per_class, seed=7):
    rng = np.random.RandomState(seed if mode == "train" else seed + 1)
    xs, ys = [], []
    yy, xx = np.mgrid[0:32, 0:32].astype(np.float32) / 32.0
    for c in range(num_classes):
        fx, fy = 1 + c % 5, 1 + (c // 5) % 5
        phase = 2 * np.pi * c / num_classes
        base = np.stack([
            np.sin(2 * np.pi * fx * xx + phase),
            np.cos(2 * np.pi * fy * yy + phase),
            np.sin(2 * np.pi * (fx * xx + fy * yy)),
        ])  # [3, 32, 32]
        for _ in range(n_per_class):
            img = base + 0.4 * rng.randn(3, 32, 32).astype(np.float32)
            img = ((img - img.min()) / (np.ptp(img) + 1e-6) * 255)
            xs.append(img.astype(np.uint8))
            ys.append(c)
    order = rng.permutation(len(xs))
    return ([xs[i] for i in order], [ys[i] for i in order])


class Cifar10(Dataset):
    """10-class 32x32 RGB images. ``data_file=None`` -> synthetic task."""

    MODE_TRAIN_MEMBERS = [f"data_batch_{i}" for i in range(1, 6)]
    MODE_TEST_MEMBERS = ["test_batch"]
    _label_key = b"labels"
    num_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        self.transform = transform
        if data_file is None:
            n = 200 if self.mode == "train" else 50
            self.images, self.labels = _synthetic(
                self.mode, self.num_classes, n)
        else:
            self.images, self.labels = self._load_archive(data_file)

    def _load_archive(self, data_file):
        wanted = (self.MODE_TRAIN_MEMBERS if self.mode == "train"
                  else self.MODE_TEST_MEMBERS)
        images, labels = [], []
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                stem = member.name.rsplit("/", 1)[-1]
                if stem not in wanted:
                    continue
                batch = pickle.load(tf.extractfile(member),
                                    encoding="bytes")
                data = batch[b"data"].reshape(-1, 3, 32, 32)
                images.extend(data)
                labels.extend(batch[self._label_key])
        if not images:
            raise ValueError(
                f"no {wanted} members found in {data_file!r} — expected "
                "the reference's cifar python archive layout")
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)
        label = np.array([self.labels[idx]], dtype=np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    """100-class variant (reference ``Cifar100``: fine labels)."""

    MODE_TRAIN_MEMBERS = ["train"]
    MODE_TEST_MEMBERS = ["test"]
    _label_key = b"fine_labels"
    num_classes = 100
