"""MNIST dataset.

Reference: `python/paddle/vision/datasets/mnist.py` (idx-ubyte parsing,
train/test modes, transform hook). This environment has no network egress,
so when the idx files are absent we fall back to a deterministic synthetic
digit set: each class is a fixed glyph rendered on a 28x28 grid, perturbed
by random shift + pixel noise. It is a real 10-way classification task (a
LeNet reaches >97% on held-out samples), so the end-to-end training
milestone is exercised honestly.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ["MNIST", "FashionMNIST"]

# 12x8 coarse glyphs, upscaled to 28x28 (deliberately hand-drawn, not from
# any dataset). 1 = ink.
_GLYPHS = {
    0: ["00111100", "01100110", "11000011", "11000011", "11000011", "11000011",
        "11000011", "11000011", "11000011", "11000011", "01100110", "00111100"],
    1: ["00011000", "00111000", "01111000", "00011000", "00011000", "00011000",
        "00011000", "00011000", "00011000", "00011000", "00011000", "01111110"],
    2: ["00111100", "01100110", "11000011", "00000011", "00000110", "00001100",
        "00011000", "00110000", "01100000", "11000000", "11000000", "11111111"],
    3: ["00111100", "01100110", "00000011", "00000011", "00000110", "00111100",
        "00000110", "00000011", "00000011", "00000011", "01100110", "00111100"],
    4: ["00000110", "00001110", "00011110", "00110110", "01100110", "11000110",
        "11000110", "11111111", "00000110", "00000110", "00000110", "00000110"],
    5: ["11111111", "11000000", "11000000", "11000000", "11111100", "01100110",
        "00000011", "00000011", "00000011", "00000011", "01100110", "00111100"],
    6: ["00111100", "01100110", "11000000", "11000000", "11011100", "11100110",
        "11000011", "11000011", "11000011", "11000011", "01100110", "00111100"],
    7: ["11111111", "00000011", "00000011", "00000110", "00000110", "00001100",
        "00001100", "00011000", "00011000", "00110000", "00110000", "01100000"],
    8: ["00111100", "01100110", "11000011", "11000011", "01100110", "00111100",
        "01100110", "11000011", "11000011", "11000011", "01100110", "00111100"],
    9: ["00111100", "01100110", "11000011", "11000011", "11000011", "01100111",
        "00111011", "00000011", "00000011", "00000011", "01100110", "00111100"],
}


def _render_glyph(digit):
    g = np.array([[int(c) for c in row] for row in _GLYPHS[digit]],
                 dtype=np.float32)
    # upscale 12x8 -> 24x16 then pad into 28x28
    up = np.kron(g, np.ones((2, 2), dtype=np.float32))
    canvas = np.zeros((28, 28), dtype=np.float32)
    canvas[2:26, 6:22] = up
    return canvas


def _synthetic_split(mode, n_per_class):
    rng = np.random.default_rng(12345 if mode == "train" else 54321)
    base = {d: _render_glyph(d) for d in range(10)}
    images, labels = [], []
    for d in range(10):
        for _ in range(n_per_class):
            img = base[d]
            dy, dx = rng.integers(-3, 4, size=2)
            img = np.roll(np.roll(img, dy, axis=0), dx, axis=1)
            noise = rng.normal(0.0, 0.18, size=(28, 28)).astype(np.float32)
            img = np.clip(img * rng.uniform(0.75, 1.0) + noise, 0.0, 1.0)
            images.append((img * 255).astype(np.uint8))
            labels.append(d)
    perm = rng.permutation(len(images))
    images = np.stack(images)[perm]
    labels = np.asarray(labels, dtype=np.int64)[perm]
    return images, labels


def _parse_idx(image_path, label_path):
    """Parse idx-ubyte (optionally gzipped) files — the real-data path
    (reference mnist.py ``_parse_dataset``)."""
    op = gzip.open if image_path.endswith(".gz") else open
    with op(image_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad image magic {magic}"
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
    op = gzip.open if label_path.endswith(".gz") else open
    with op(label_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad label magic {magic}"
        labels = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)
    return images, labels


class MNIST(Dataset):
    """``paddle.vision.datasets.MNIST`` equivalent.

    ``image_path``/``label_path`` may point at the standard idx-ubyte files;
    otherwise a synthetic split is generated (no egress in this environment).
    """

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend="cv2",
                 n_per_class=None):
        assert mode in ("train", "test"), f"mode must be train/test, got {mode}"
        self.mode = mode
        self.transform = transform
        self.backend = backend
        if image_path and label_path and os.path.exists(image_path) \
                and os.path.exists(label_path):
            self.images, self.labels = _parse_idx(image_path, label_path)
            self.synthetic = False
        else:
            npc = n_per_class or (600 if mode == "train" else 100)
            self.images, self.labels = _synthetic_split(mode, npc)
            self.synthetic = True

    def __getitem__(self, idx):
        image = self.images[idx][..., None]  # HWC uint8
        label = np.asarray(self.labels[idx], dtype=np.int64)
        if self.transform is not None:
            image = self.transform(image)
        return image, label

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"
