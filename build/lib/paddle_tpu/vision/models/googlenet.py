"""GoogLeNet / Inception v1 (reference:
`python/paddle/vision/models/googlenet.py`). Returns (main, aux1, aux2)
logits like the reference; aux heads are identity in eval mode.
"""

from __future__ import annotations

from ... import nn
from ...tensor import manipulation

__all__ = ["GoogLeNet", "googlenet"]


def _conv_relu(inp, oup, k, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(inp, oup, k, stride=stride, padding=padding), nn.ReLU())


class Inception(nn.Layer):
    def __init__(self, inp, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _conv_relu(inp, c1, 1)
        self.b2 = nn.Sequential(_conv_relu(inp, c3r, 1),
                                _conv_relu(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_conv_relu(inp, c5r, 1),
                                _conv_relu(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _conv_relu(inp, proj, 1))

    def forward(self, x):
        return manipulation.concat(
            [self.b1(x), self.b2(x), self.b3(x), self.b4(x)], axis=1)


class AuxHead(nn.Layer):
    def __init__(self, inp, num_classes):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((4, 4))
        self.conv = _conv_relu(inp, 128, 1)
        self.fc1 = nn.Linear(128 * 16, 1024)
        self.relu = nn.ReLU()
        self.dropout = nn.Dropout(0.7)
        self.fc2 = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x))
        x = self.relu(self.fc1(x.reshape([x.shape[0], -1])))
        return self.fc2(self.dropout(x))


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _conv_relu(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, padding=1),
            _conv_relu(64, 64, 1),
            _conv_relu(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, padding=1))
        self.inc3a = Inception(192, 64, 96, 128, 16, 32, 32)
        self.inc3b = Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc4a = Inception(480, 192, 96, 208, 16, 48, 64)
        self.inc4b = Inception(512, 160, 112, 224, 24, 64, 64)
        self.inc4c = Inception(512, 128, 128, 256, 24, 64, 64)
        self.inc4d = Inception(512, 112, 144, 288, 32, 64, 64)
        self.inc4e = Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, padding=1)
        self.inc5a = Inception(832, 256, 160, 320, 32, 128, 128)
        self.inc5b = Inception(832, 384, 192, 384, 48, 128, 128)
        if num_classes > 0:
            self.aux1 = AuxHead(512, num_classes)
            self.aux2 = AuxHead(528, num_classes)
            self.dropout = nn.Dropout(0.4)
            self.fc = nn.Linear(1024, num_classes)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.inc3b(self.inc3a(self.stem(x)))
        x = self.inc4a(self.pool3(x))
        aux1 = self.aux1(x) if (self.num_classes > 0 and self.training) \
            else None
        x = self.inc4d(self.inc4c(self.inc4b(x)))
        aux2 = self.aux2(x) if (self.num_classes > 0 and self.training) \
            else None
        x = self.inc5b(self.inc5a(self.pool4(self.inc4e(x))))
        x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.reshape([x.shape[0], -1])))
        return x, aux1, aux2


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return GoogLeNet(**kwargs)
