"""MobileNetV3 (reference: `python/paddle/vision/models/mobilenetv3.py`)."""

from __future__ import annotations

from ... import nn
from .mobilenetv2 import _make_divisible

__all__ = ["MobileNetV3Small", "MobileNetV3Large", "mobilenet_v3_small",
           "mobilenet_v3_large"]


class SqueezeExcite(nn.Layer):
    def __init__(self, ch, squeeze_ch):
        super().__init__()
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        self.fc1 = nn.Conv2D(ch, squeeze_ch, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_ch, ch, 1)
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class ConvBNAct(nn.Sequential):
    def __init__(self, inp, oup, k, stride=1, groups=1, act=None):
        layers = [
            nn.Conv2D(inp, oup, k, stride=stride, padding=(k - 1) // 2,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(oup)]
        if act is not None:
            layers.append(act())
        super().__init__(*layers)


class InvertedResidualV3(nn.Layer):
    def __init__(self, inp, hidden, oup, k, stride, use_se, use_hs):
        super().__init__()
        act = nn.Hardswish if use_hs else nn.ReLU
        self.use_res = stride == 1 and inp == oup
        layers = []
        if hidden != inp:
            layers.append(ConvBNAct(inp, hidden, 1, act=act))
        layers.append(ConvBNAct(hidden, hidden, k, stride=stride,
                                groups=hidden, act=act))
        if use_se:
            layers.append(SqueezeExcite(hidden,
                                        _make_divisible(hidden // 4)))
        layers.append(ConvBNAct(hidden, oup, 1, act=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


# (kernel, hidden, out, use_se, use_hs, stride)
_LARGE = [
    (3, 16, 16, False, False, 1), (3, 64, 24, False, False, 2),
    (3, 72, 24, False, False, 1), (5, 72, 40, True, False, 2),
    (5, 120, 40, True, False, 1), (5, 120, 40, True, False, 1),
    (3, 240, 80, False, True, 2), (3, 200, 80, False, True, 1),
    (3, 184, 80, False, True, 1), (3, 184, 80, False, True, 1),
    (3, 480, 112, True, True, 1), (3, 672, 112, True, True, 1),
    (5, 672, 160, True, True, 2), (5, 960, 160, True, True, 1),
    (5, 960, 160, True, True, 1),
]
_SMALL = [
    (3, 16, 16, True, False, 2), (3, 72, 24, False, False, 2),
    (3, 88, 24, False, False, 1), (5, 96, 40, True, True, 2),
    (5, 240, 40, True, True, 1), (5, 240, 40, True, True, 1),
    (5, 120, 48, True, True, 1), (5, 144, 48, True, True, 1),
    (5, 288, 96, True, True, 2), (5, 576, 96, True, True, 1),
    (5, 576, 96, True, True, 1),
]


class _MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_ch, num_classes=1000, scale=1.0):
        super().__init__()
        self.num_classes = num_classes
        inp = _make_divisible(16 * scale)
        layers = [ConvBNAct(3, inp, 3, stride=2, act=nn.Hardswish)]
        for k, hidden, oup, se, hs, s in cfg:
            hidden = _make_divisible(hidden * scale)
            oup = _make_divisible(oup * scale)
            layers.append(InvertedResidualV3(inp, hidden, oup, k, s, se, hs))
            inp = oup
        last_conv = _make_divisible(6 * inp)
        layers.append(ConvBNAct(inp, last_conv, 1, act=nn.Hardswish))
        self.features = nn.Sequential(*layers)
        self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(last_conv, last_ch), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_ch, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        if self.num_classes > 0:
            x = self.classifier(x.reshape([x.shape[0], -1]))
        return x


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__(_SMALL, 1024, num_classes, scale)


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__(_LARGE, 1280, num_classes, scale)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Large(scale=scale, **kwargs)
