"""DenseNet (reference: `python/paddle/vision/models/densenet.py`).

Dense blocks concatenate along channels; XLA keeps the concats as
views feeding the next conv's im2col, so no quadratic copies.
"""

from __future__ import annotations

from ... import nn
from ...tensor import manipulation

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class DenseLayer(nn.Layer):
    def __init__(self, inp, growth, bn_size=4, dropout=0.0):
        super().__init__()
        self.norm1 = nn.BatchNorm2D(inp)
        self.relu = nn.ReLU()
        self.conv1 = nn.Conv2D(inp, bn_size * growth, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(bn_size * growth)
        self.conv2 = nn.Conv2D(bn_size * growth, growth, 3, padding=1,
                               bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.norm1(x)))
        out = self.conv2(self.relu(self.norm2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return manipulation.concat([x, out], axis=1)


class Transition(nn.Sequential):
    def __init__(self, inp, oup):
        super().__init__(
            nn.BatchNorm2D(inp), nn.ReLU(),
            nn.Conv2D(inp, oup, 1, bias_attr=False),
            nn.AvgPool2D(2, stride=2))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0,
                 num_classes=1000, with_pool=True):
        super().__init__()
        if layers not in _CFG:
            raise ValueError(f"layers must be one of {sorted(_CFG)}")
        init_ch, growth, blocks = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_ch, 7, stride=2, padding=3, bias_attr=False),
            nn.BatchNorm2D(init_ch), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        ch = init_ch
        feats = []
        for i, n in enumerate(blocks):
            for _ in range(n):
                feats.append(DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if i != len(blocks) - 1:
                feats.append(Transition(ch, ch // 2))
                ch //= 2
        feats += [nn.BatchNorm2D(ch), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.features(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape([x.shape[0], -1]))
        return x


def _factory(depth):
    def build(pretrained=False, **kwargs):
        if pretrained:
            raise NotImplementedError("pretrained weights are not bundled")
        return DenseNet(layers=depth, **kwargs)
    return build


densenet121 = _factory(121)
densenet161 = _factory(161)
densenet169 = _factory(169)
densenet201 = _factory(201)
densenet264 = _factory(264)
