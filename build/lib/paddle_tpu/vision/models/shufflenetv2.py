"""ShuffleNetV2 (reference: `python/paddle/vision/models/shufflenetv2.py`).

Channel split + shuffle; the shuffle is `F.channel_shuffle` (a pure
relayout XLA folds into the surrounding convs).
"""

from __future__ import annotations

from ... import nn
from ...nn import functional as F
from ...tensor import manipulation

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_5",
           "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0"]

_STAGE_OUT = {
    0.25: [24, 24, 48, 96, 512],
    0.5: [24, 48, 96, 192, 1024],
    1.0: [24, 116, 232, 464, 1024],
    1.5: [24, 176, 352, 704, 1024],
    2.0: [24, 244, 488, 976, 2048],
}


def _conv_bn(inp, oup, k, stride=1, groups=1, act=True):
    layers = [nn.Conv2D(inp, oup, k, stride=stride, padding=(k - 1) // 2,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(oup)]
    if act:
        layers.append(nn.ReLU())
    return nn.Sequential(*layers)


class ShuffleUnit(nn.Layer):
    def __init__(self, inp, oup, stride):
        super().__init__()
        self.stride = stride
        branch = oup // 2
        if stride == 1:
            self.branch2 = nn.Sequential(
                _conv_bn(inp // 2, branch, 1),
                _conv_bn(branch, branch, 3, groups=branch, act=False),
                _conv_bn(branch, branch, 1))
        else:
            self.branch1 = nn.Sequential(
                _conv_bn(inp, inp, 3, stride=stride, groups=inp, act=False),
                _conv_bn(inp, branch, 1))
            self.branch2 = nn.Sequential(
                _conv_bn(inp, branch, 1),
                _conv_bn(branch, branch, 3, stride=stride, groups=branch,
                         act=False),
                _conv_bn(branch, branch, 1))

    def forward(self, x):
        if self.stride == 1:
            half = x.shape[1] // 2
            x1 = x[:, :half]
            x2 = x[:, half:]
            out = manipulation.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = manipulation.concat([self.branch1(x), self.branch2(x)],
                                      axis=1)
        return F.channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"scale must be one of {sorted(_STAGE_OUT)}")
        ch = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _conv_bn(3, ch[0], 3, stride=2)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        inp = ch[0]
        for stage_idx, repeats in enumerate([4, 8, 4]):
            oup = ch[stage_idx + 1]
            units = [ShuffleUnit(inp, oup, stride=2)]
            units += [ShuffleUnit(oup, oup, stride=1)
                      for _ in range(repeats - 1)]
            stages.append(nn.Sequential(*units))
            inp = oup
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn(inp, ch[4], 1)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(ch[4], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.maxpool(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.reshape([x.shape[0], -1]))
        return x


def _factory(scale):
    def build(pretrained=False, **kwargs):
        if pretrained:
            raise NotImplementedError("pretrained weights are not bundled")
        return ShuffleNetV2(scale=scale, **kwargs)
    return build


shufflenet_v2_x0_25 = _factory(0.25)
shufflenet_v2_x0_5 = _factory(0.5)
shufflenet_v2_x1_0 = _factory(1.0)
shufflenet_v2_x1_5 = _factory(1.5)
shufflenet_v2_x2_0 = _factory(2.0)
