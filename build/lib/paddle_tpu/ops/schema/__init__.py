"""Single-source op schema (the reference's `paddle/phi/api/yaml/ops.yaml`).

``ops.yaml`` is the machine-readable inventory of every registered op:
name, defining module, full Python signature (parameter names, kinds,
default reprs), differentiability, and Tensor-method attachments. Two
consumers keep it honest:

- :mod:`paddle_tpu._C_ops` is *generated* from it at import — the
  reference's generated dispatch surface (`python/paddle/_C_ops.py:20`)
  — so an op missing from the YAML is not reachable via ``_C_ops``.
- ``validate_against_registry()`` (run in tests) diffs the YAML against
  the live ``@defop`` registry in both directions, including signatures
  and flags, so schema and implementation cannot drift apart — the
  discipline the reference enforces by generating C++ from the YAML
  (SURVEY §2.2 "codegen from day one or drown").

Regenerate after adding ops: ``python -m paddle_tpu.ops.schema --update``.
"""

from __future__ import annotations

import inspect
import os

import yaml

__all__ = ["load_schema", "snapshot_registry", "validate_against_registry",
           "SCHEMA_PATH"]

SCHEMA_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "ops.yaml")

_cache = None


def load_schema():
    """Parse ops.yaml → {op_name: entry dict}."""
    global _cache
    if _cache is None:
        with open(SCHEMA_PATH) as f:
            entries = yaml.safe_load(f)
        _cache = {e["op"]: e for e in entries}
        if len(_cache) != len(entries):
            seen, dups = set(), []
            for e in entries:
                if e["op"] in seen:
                    dups.append(e["op"])
                seen.add(e["op"])
            raise ValueError(f"duplicate ops in ops.yaml: {dups}")
    return _cache


def _signature_entry(fn):
    """Serialize a Python signature to a stable, comparable form."""
    params = []
    for p in inspect.signature(fn).parameters.values():
        entry = {"name": p.name}
        if p.kind == inspect.Parameter.VAR_POSITIONAL:
            entry["kind"] = "*args"
        elif p.kind == inspect.Parameter.VAR_KEYWORD:
            entry["kind"] = "**kwargs"
        elif p.kind == inspect.Parameter.KEYWORD_ONLY:
            entry["kind"] = "kwonly"
        if p.default is not inspect.Parameter.empty:
            entry["default"] = repr(p.default)
        params.append(entry)
    return params


def _import_op_surface():
    """Import every op-bearing module so the registry is complete.

    The top-level package keeps heavy subpackages (vision, text,
    geometric) lazy; the schema is the inventory of ALL ops, so the
    snapshot/validation path must load them deterministically."""
    import importlib

    for mod in ("paddle_tpu", "paddle_tpu.vision.ops", "paddle_tpu.text",
                "paddle_tpu.geometric", "paddle_tpu.signal",
                "paddle_tpu.incubate.nn.functional",
                "paddle_tpu.ops.schema.surface"):
        importlib.import_module(mod)


def snapshot_registry():
    """The live @defop registry in schema form (sorted by op name)."""
    from paddle_tpu.tensor.registry import OPS

    _import_op_surface()
    if not OPS:
        raise RuntimeError("op registry empty — import paddle_tpu first")
    out = []
    for name in sorted(OPS):
        info = OPS[name]
        entry = {
            "op": name,
            "module": info["module"],
            "args": _signature_entry(info["fn"]),
            "differentiable": bool(info["differentiable"]),
        }
        if info.get("method"):
            entry["method"] = info["method"]
        if info.get("inplace"):
            entry["inplace"] = info["inplace"]
        out.append(entry)
    return out


def validate_against_registry():
    """Return a list of human-readable drift errors (empty == in sync)."""
    schema = load_schema()
    live = {e["op"]: e for e in snapshot_registry()}
    errors = []
    for name in sorted(set(schema) - set(live)):
        errors.append(f"ops.yaml lists '{name}' but no @defop registers it")
    for name in sorted(set(live) - set(schema)):
        errors.append(f"op '{name}' ({live[name]['module']}) is registered "
                      "but missing from ops.yaml — run "
                      "`python -m paddle_tpu.ops.schema --update`")
    for name in sorted(set(live) & set(schema)):
        for key in ("module", "args", "differentiable", "method", "inplace"):
            want, got = schema[name].get(key), live[name].get(key)
            if want != got:
                errors.append(
                    f"op '{name}' drifted in '{key}': "
                    f"ops.yaml={want!r} registry={got!r}")
    return errors


def write_schema(path=None):
    entries = snapshot_registry()
    with open(path or SCHEMA_PATH, "w") as f:
        f.write("# Generated op inventory — the single-source schema.\n"
                "# Regenerate: python -m paddle_tpu.ops.schema --update\n"
                "# (tests fail if this file and the @defop registry "
                "disagree)\n")
        yaml.safe_dump(entries, f, sort_keys=False, width=79)
    return len(entries)
