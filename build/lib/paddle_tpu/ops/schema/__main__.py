"""CLI: ``python -m paddle_tpu.ops.schema --update|--check``."""

import argparse
import sys


def main():
    ap = argparse.ArgumentParser()
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--update", action="store_true",
                   help="rewrite ops.yaml from the live registry")
    g.add_argument("--check", action="store_true",
                   help="exit 1 if ops.yaml drifted from the registry")
    args = ap.parse_args()

    import paddle_tpu  # noqa: F401  — registers every op
    from . import validate_against_registry, write_schema

    if args.update:
        n = write_schema()
        print(f"wrote {n} ops")
        return 0
    errors = validate_against_registry()
    for e in errors:
        print(e, file=sys.stderr)
    print(f"{'DRIFTED' if errors else 'OK'}")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
