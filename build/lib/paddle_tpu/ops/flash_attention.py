"""Flash attention as Pallas TPU kernels (forward + backward), with GQA.

Capability reference: `python/paddle/nn/functional/flash_attention.py:147`
and the external flash-attn v2 library the reference dynloads
(`paddle/phi/backends/dynload/flashattn.cc`). This is an original
blockwise-softmax implementation in Pallas (TPU-first: MXU matmuls with
fp32 accumulation, VMEM-resident K/V per head, online max/sum rescaling —
no O(S^2) materialization in HBM).

Layout: inputs [B, S, H, D] (the reference's layout). Grouped-query
attention (H query heads sharing H_kv key/value heads, H % H_kv == 0) is
native: the grid is (batch, q_head, q_block) and the K/V BlockSpec index
map points q-head ``h`` at kv-head ``h // group``, so no K/V replication
ever materializes in HBM — the MXU reads the shared heads straight from
VMEM.

Backward uses the standard recomputation split:
  dV_j = sum_i P_ij^T dO_i
  dK_j = sum_i (P_ij ∘ (dP_ij - D_i))^T Q_i * scale
  dQ_i = sum_j (P_ij ∘ (dP_ij - D_i)) K_j * scale
with P recomputed from the saved log-sum-exp rows. The dK/dV kernel runs
per kv-head and statically unrolls over its ``group`` query heads.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
try:  # pltpu import works on CPU too (interpret mode)
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..framework.tensor import run_op

__all__ = ["flash_attention", "supported"]

BLOCK_Q = 128
BLOCK_K = 128
NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def supported(q, k, v, attn_mask, causal):
    """Pallas path preconditions; anything else falls back to XLA."""
    if not _HAS_PLTPU:
        return False
    if attn_mask is not None:
        return False
    qs = q.shape if not hasattr(q, "_data") else q._data.shape
    ks = k.shape if not hasattr(k, "_data") else k._data.shape
    vs = v.shape if not hasattr(v, "_data") else v._data.shape
    if len(qs) != 4 or len(ks) != 4:
        return False
    if tuple(vs) != tuple(ks):
        return False
    b, sq, h, d = qs
    if ks[0] != b:
        return False
    sk, hk = ks[1], ks[2]
    if hk == 0 or h % hk:
        return False
    if ks[3] != d:
        return False
    # VMEM budget: the dK/dV kernel blocks (group, sq, d) Q and dO into
    # VMEM; the fwd kernel streams the full (sk, d) K and V. Stay well
    # under the ~16 MB/core VMEM or the pallas_call fails to map.
    itemsize = jnp.dtype(q.dtype).itemsize if hasattr(q, "dtype") else 4
    group = h // hk
    if 2 * group * sq * d * itemsize > 12 * 1024 * 1024:
        return False
    if 2 * sk * d * itemsize > 12 * 1024 * 1024:
        return False
    if causal and sq > sk:
        # bottom-right alignment gives offset < 0: leading q-blocks would
        # see zero keys (l == 0 -> 0/0 NaN rows); let the XLA path mask them
        return False
    if sq < BLOCK_Q or sk < BLOCK_K:
        return False
    if sq % BLOCK_Q or sk % BLOCK_K:
        return False
    if d % 8 or d > 256:
        return False
    return True


# ---------------------------------------------------------------------------
# forward kernel: one (batch, q_head, q-block) program; K/V stream in VMEM
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k, offset):
    # ``offset = sk - sq``: causal alignment is bottom-right (last query
    # attends to every key), matching the naive fallback in
    # nn/functional/attention.py
    q = q_ref[0, 0].astype(jnp.float32)         # [Bq, D]
    sk = k_ref.shape[2]
    num_kb = sk // block_k
    qi = pl.program_id(2)

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [Bq, Bk]
        if causal:
            q_pos = qi * q.shape[0] + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0) + offset
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)        # [Bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    bq, d = q.shape
    init = (jnp.zeros((bq, d), jnp.float32),
            jnp.full((bq, 1), NEG_INF, jnp.float32),
            jnp.zeros((bq, 1), jnp.float32))
    if causal:
        # only blocks with k_start <= last query position contribute
        last = (qi + 1) * bq + offset
        num_iters = jax.lax.min(num_kb, pl.cdiv(last, block_k))
    else:
        num_iters = num_kb
    acc, m, l = jax.lax.fori_loop(0, num_iters, body, init)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    # lse is [Bq, 1]: the trailing singleton keeps the Mosaic block 2-D
    # (blocks of a (B, H, Sq) array would be (1, Bq) — second-to-last dim 1
    # fails the sublane-divisibility rule on real TPU lowering)
    lse_ref[0, 0] = m + jnp.log(l)


def _fwd(q, k, v, scale, causal, group):
    """q: [B, H, Sq, D]; k/v: [B, Hk, Sk, D] head-major."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    grid = (b, h, sq // BLOCK_Q)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal,
                          block_k=BLOCK_K, offset=sk - sq),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, BLOCK_Q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi: (bi, hi // group, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, BLOCK_Q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, BLOCK_Q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_k, offset):
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]                         # [Bq, 1]
    delta = delta_ref[0, 0]                     # [Bq, 1]
    sk = k_ref.shape[2]
    num_kb = sk // block_k
    qi = pl.program_id(2)
    bq = q.shape[0]

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0) + offset
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    if causal:
        num_iters = jax.lax.min(num_kb,
                                pl.cdiv((qi + 1) * bq + offset, block_k))
    else:
        num_iters = num_kb
    dq = jax.lax.fori_loop(0, num_iters, body,
                           jnp.zeros(q.shape, jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, offset,
                    group):
    k = k_ref[0, 0].astype(jnp.float32)          # [Bk, D]
    v = v_ref[0, 0].astype(jnp.float32)
    sq = q_ref.shape[2]
    num_qb = sq // block_q
    ki = pl.program_id(2)
    bk = k.shape[0]

    def make_body(gi):
        def body(i, carry):
            dk, dv = carry
            q = q_ref[0, gi, pl.ds(i * block_q, block_q), :] \
                .astype(jnp.float32)
            do = do_ref[0, gi, pl.ds(i * block_q, block_q), :] \
                .astype(jnp.float32)
            lse = lse_ref[0, gi, pl.ds(i * block_q, block_q), :]
            delta = delta_ref[0, gi, pl.ds(i * block_q, block_q), :]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if causal:
                q_pos = i * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 0) + offset
                k_pos = ki * bk + jax.lax.broadcasted_iota(
                    jnp.int32, s.shape, 1)
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            p = jnp.exp(s - lse)                  # [Bq, Bk]
            dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta) * scale
            dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
            return dk, dv
        return body

    if causal:
        # q blocks whose last position precedes this k block never attend
        start = jax.lax.max(0, (ki * bk - offset) // block_q)
    else:
        start = 0
    carry = (jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32))
    for gi in range(group):  # static unroll over the shared query heads
        carry = jax.lax.fori_loop(start, num_qb, make_body(gi), carry)
    dk, dv = carry
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(scale, causal, group, res, g):
    qh, kh, vh, out, lse = res                   # head-major
    b, h, sq, d = qh.shape
    hk, sk = kh.shape[1], kh.shape[2]
    do = g
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)      # [B, H, Sq, 1]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=BLOCK_K, offset=sk - sq),
        grid=(b, h, sq // BLOCK_Q),
        in_specs=[
            pl.BlockSpec((1, 1, BLOCK_Q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi: (bi, hi // group, 0, 0)),
            pl.BlockSpec((1, 1, BLOCK_Q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, BLOCK_Q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, BLOCK_Q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, BLOCK_Q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), qh.dtype),
        interpret=_interpret(),
    )(qh, kh, vh, do, lse, delta)
    # per-kv-head: the group of query heads is a contiguous head block
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=BLOCK_Q, offset=sk - sq, group=group),
        grid=(b, hk, sk // BLOCK_K),
        in_specs=[
            pl.BlockSpec((1, group, sq, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, BLOCK_K, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, BLOCK_K, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, group, sq, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, group, sq, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, group, sq, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, BLOCK_K, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, BLOCK_K, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hk, sk, d), kh.dtype),
            jax.ShapeDtypeStruct((b, hk, sk, d), vh.dtype),
        ],
        interpret=_interpret(),
    )(qh, kh, vh, do, lse, delta)
    return dq, dk, dv


@functools.lru_cache(maxsize=64)
def _make_flash(scale, causal, group):
    """Build the custom-vjp function for a given static config. Memoized:
    JAX's compilation cache keys on callable identity, so a fresh closure
    per call would recompile the kernels every eager step."""

    @jax.custom_vjp
    def fa(q, k, v):
        # [B, S, H, D] -> head-major [B, H, S, D]
        out, _ = _fwd(q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
                      v.transpose(0, 2, 1, 3), scale, causal, group)
        return out.transpose(0, 2, 1, 3)

    def fa_fwd(q, k, v):
        qh = q.transpose(0, 2, 1, 3)
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        out, lse = _fwd(qh, kh, vh, scale, causal, group)
        return out.transpose(0, 2, 1, 3), (qh, kh, vh, out, lse)

    def fa_bwd(res, g):
        dq, dk, dv = _bwd(scale, causal, group, res,
                          g.transpose(0, 2, 1, 3))
        to_bshd = lambda x: x.transpose(0, 2, 1, 3)
        return to_bshd(dq), to_bshd(dk), to_bshd(dv)

    fa.defvjp(fa_fwd, fa_bwd)
    return fa


def flash_attention(query, key, value, attn_mask=None, causal=False,
                    scale=None):
    """Tape-integrated flash attention; q [B,S,H,D], k/v [B,S,Hk,D] with
    H % Hk == 0 (GQA/MQA native — no K/V replication)."""
    if not supported(query, key, value, attn_mask, causal):
        raise ValueError(
            "flash_attention Pallas preconditions not met (need 4-D "
            f"[B,S,H,D], S % {BLOCK_Q} == 0, head_dim % 8 == 0 and <= 256, "
            "num_heads divisible by num_kv_heads, attn_mask None); use "
            "scaled_dot_product_attention for the XLA fallback")
    qs = query._data.shape if hasattr(query, "_data") else query.shape
    ks = key._data.shape if hasattr(key, "_data") else key.shape
    b, sq, h, d = qs
    hk = ks[2]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    fa = _make_flash(s, bool(causal), h // hk)
    return run_op("flash_attention", fa, (query, key, value))
