"""Paged attention: decode-step GQA attention over a paged KV pool.

Capability reference: the reference's serving attention with a paged KV
cache (`paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu`
+ `masked_multihead_attention`). TPU-native design: a Pallas kernel over
a global page pool with per-sequence block tables delivered through
scalar prefetch — the block table entry picks which HBM page each grid
step streams into VMEM (`PrefetchScalarGridSpec` index maps), so KV for
a sequence never needs to be contiguous and batches of ragged sequences
decode in one launch.

Shapes:
  q             [B, H, D]           one new token per sequence
  k_pages       [P, Hk, page_size, D]   global pool, any page owner
                                        (head-major: the Mosaic lowering
                                        needs the last two block dims to
                                        tile as (page, D))
  v_pages       [P, Hk, page_size, D]
  block_tables  [B, max_pages] int32    page ids per sequence (row-major
                                        position order; unused tail
                                        entries may hold anything — they
                                        are clamped into [0, P) before
                                        reaching the index map)
  context_lens  [B] int32              valid tokens per sequence,
                                        *including* the current one
                                        (its K/V must already be written)
  -> out        [B, H, D]

The kernel runs grid (B, Hk, max_pages) with one online-softmax
accumulator in VMEM scratch per (sequence, kv-head); query heads of the
same GQA group ride along as a [group, D] MXU operand. Pages past
ceil(context_len / page_size) are skipped (no HBM read cost beyond the
prefetched block spec's page — the table tail can point at page 0).
Decode is inference-only: no VJP is defined.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from ..framework.tensor import run_op

__all__ = ["paged_attention", "paged_attention_xla", "supported"]

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def supported(q, k_pages, v_pages, block_tables, context_lens):
    if not _HAS_PLTPU:
        return False
    qs = getattr(q, "_data", q).shape
    ks = getattr(k_pages, "_data", k_pages).shape
    bt = getattr(block_tables, "_data", block_tables).shape
    cl = getattr(context_lens, "_data", context_lens).shape
    if len(qs) != 3 or len(ks) != 4 or len(bt) != 2 or len(cl) != 1:
        return False
    b, h, d = qs
    p, hk, page_size, dk = ks
    if getattr(v_pages, "_data", v_pages).shape != tuple(ks):
        return False
    if d != dk or hk == 0 or h % hk or bt[0] != b or cl[0] != b:
        return False
    if d % 8 or d > 256 or page_size % 8:
        return False
    return True


def _decode_kernel(tables_ref, lens_ref,  # scalar prefetch
                   q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, page_size, scale):
    b = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = lens_ref[b]
    page_start = p * page_size

    @pl.when(page_start < ctx)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32) * scale      # [G, D]
        k = k_ref[0, 0].astype(jnp.float32)              # [page, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        kpos = page_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos < ctx, s, NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(p == num_pages - 1)
    def _finish():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


@functools.lru_cache(maxsize=32)
def _make_paged(scale, page_size, group, interpret):
    def call(q4, k_pages, v_pages, tables, lens):
        b, hk, g, d = q4.shape
        max_pages = tables.shape[1]
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, hk, max_pages),
            in_specs=[
                pl.BlockSpec((1, 1, g, d),
                             lambda bi, hi, pi, tables, lens: (bi, hi, 0, 0)),
                # the prefetched block table picks the HBM page to stream
                pl.BlockSpec((1, 1, page_size, d),
                             lambda bi, hi, pi, tables, lens:
                             (tables[bi, pi], hi, 0, 0)),
                pl.BlockSpec((1, 1, page_size, d),
                             lambda bi, hi, pi, tables, lens:
                             (tables[bi, pi], hi, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, g, d),
                lambda bi, hi, pi, tables, lens: (bi, hi, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((g, d), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
                pltpu.VMEM((g, 1), jnp.float32),
            ],
        )
        return pl.pallas_call(
            functools.partial(_decode_kernel, page_size=page_size,
                              scale=scale),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, hk, g, d), q4.dtype),
            interpret=interpret,
        )(tables, lens, q4, k_pages, v_pages)

    return call


def _paged_impl(q, k_pages, v_pages, block_tables, context_lens, scale):
    b, h, d = q.shape
    hk = k_pages.shape[1]
    group = h // hk
    page_size = k_pages.shape[2]
    q4 = q.reshape(b, hk, group, d)
    call = _make_paged(scale, page_size, group, _interpret())
    # Tail entries past a sequence's last page are never *read* for the
    # output, but they still feed the Pallas index map — clamp so an
    # arbitrary tail value can't index the page pool out of bounds
    # (unspecified behavior in Mosaic).
    tables = jnp.clip(block_tables.astype(jnp.int32), 0,
                      k_pages.shape[0] - 1)
    out = call(q4, k_pages, v_pages, tables,
               context_lens.astype(jnp.int32))
    return out.reshape(b, h, d)


def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    scale=None):
    """Decode-step attention over the paged pool (see module docstring).
    Tape-integrated but non-differentiable (serving path)."""
    if not supported(q, k_pages, v_pages, block_tables, context_lens):
        raise ValueError(
            "paged_attention preconditions not met: need q [B,H,D], pages "
            "[P,Hk,page,D] (page % 8 == 0, D % 8 == 0, D <= 256, "
            "H % Hk == 0), tables [B,max_pages], lens [B]")
    d = getattr(q, "_data", q).shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)

    def fn(q, kp, vp, bt, cl):
        return _paged_impl(q, kp, vp, bt, cl, s)

    return run_op("paged_attention", fn,
                  (q, k_pages, v_pages, block_tables, context_lens),
                  differentiable=False)


def paged_attention_xla(q, k_pages, v_pages, block_tables, context_lens,
                        scale=None):
    """XLA reference path: gather pages to a contiguous [B, S, Hk, D]
    window, mask, softmax. Semantically identical; used for parity tests
    and as the fallback where Pallas is unavailable."""
    q, k_pages, v_pages, block_tables, context_lens = (
        getattr(a, "_data", a)
        for a in (q, k_pages, v_pages, block_tables, context_lens))
    b, h, d = q.shape
    p, hk, page_size, _ = k_pages.shape
    group = h // hk
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B, max_pages, Hk, page, D] -> [B, S, Hk, D]
    k = jnp.swapaxes(k_pages[block_tables], 2, 3).reshape(b, -1, hk, d)
    v = jnp.swapaxes(v_pages[block_tables], 2, 3).reshape(b, -1, hk, d)
    kq = jnp.repeat(k, group, axis=2)
    vq = jnp.repeat(v, group, axis=2)
    logits = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                        kq.astype(jnp.float32)) * s
    kpos = jnp.arange(k.shape[1])[None, None, :]
    logits = jnp.where(kpos < context_lens[:, None, None], logits, NEG_INF)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", w, vq.astype(jnp.float32)) \
        .astype(q.dtype)
