"""``paddle.audio`` — audio feature extraction.

Reference: `python/paddle/audio/` (`functional/window.py`,
`functional/functional.py` hz<->mel + filterbanks, `features/layers.py`
Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC). TPU-native: the STFT
is framing + window + ``rfft`` (XLA's real DFT); mel projection is one
matmul riding the MXU. Everything is tape-recorded and differentiable.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..framework.tensor import Tensor, run_op

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "compute_fbank_matrix",
           "Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def get_window(window, win_length, fftbins=True):
    """Reference functional/window.py get_window (dense set)."""
    n = win_length
    if window == "hann":
        w = np.hanning(n + 1)[:-1] if fftbins else np.hanning(n)
    elif window == "hamming":
        w = np.hamming(n + 1)[:-1] if fftbins else np.hamming(n)
    elif window == "blackman":
        w = np.blackman(n + 1)[:-1] if fftbins else np.blackman(n)
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(n)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(w.astype("float32"))


def hz_to_mel(freq, htk=False):
    """Reference functional.py hz_to_mel (slaney default)."""
    f = np.asarray(freq, np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, out)
    return float(out) if np.isscalar(freq) else out


def mel_to_hz(mel, htk=False):
    m = np.asarray(mel, np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    return float(out) if np.isscalar(mel) else out


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """[n_mels, n_fft//2 + 1] mel filterbank (reference functional.py)."""
    f_max = f_max or sr / 2
    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2, n_bins)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, n_bins))
    for i in range(n_mels):
        lo, ctr, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-10)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return Tensor(fb.astype(dtype))


class Spectrogram(nn.Layer):
    """STFT power spectrogram (reference features/layers.py Spectrogram).
    Input [B, T] -> [B, n_fft//2+1, frames]."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        w = get_window(window, self.win_length)._data
        if self.win_length < n_fft:  # center-pad the window to n_fft
            pad = n_fft - self.win_length
            w = jnp.pad(w, (pad // 2, pad - pad // 2))
        self.register_buffer("window", Tensor(w))

    def forward(self, x):
        n_fft, hop, center, pad_mode, power = (
            self.n_fft, self.hop, self.center, self.pad_mode, self.power)

        def fn(a, w):
            if center:
                a = jnp.pad(a, ((0, 0), (n_fft // 2, n_fft // 2)),
                            mode=pad_mode)
            n_frames = 1 + (a.shape[1] - n_fft) // hop
            idx = (jnp.arange(n_frames)[:, None] * hop
                   + jnp.arange(n_fft)[None, :])
            frames = a[:, idx] * w                       # [B, F, n_fft]
            spec = jnp.fft.rfft(frames, axis=-1)         # [B, F, bins]
            mag = jnp.abs(spec)
            if power is not None:
                mag = mag ** power
            return jnp.swapaxes(mag, 1, 2)                # [B, bins, F]

        return run_op("spectrogram", fn, (x, self.window))


class MelSpectrogram(nn.Layer):
    """Spectrogram -> mel filterbank (reference MelSpectrogram)."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode)
        self.register_buffer(
            "fbank", compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                          htk, norm, dtype))

    def forward(self, x):
        spec = self.spectrogram(x)
        return run_op("mel_project",
                      lambda s, fb: jnp.einsum("mf,bft->bmt", fb, s),
                      (spec, self.fbank))


class LogMelSpectrogram(nn.Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, center, pad_mode, n_mels,
                                  f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        m = self.mel(x)
        amin, ref, top_db = self.amin, self.ref_value, self.top_db

        def fn(a):
            db = 10.0 * jnp.log10(jnp.maximum(a, amin))
            db = db - 10.0 * math.log10(max(amin, ref))
            if top_db is not None:
                db = jnp.maximum(db, db.max() - top_db)
            return db

        return run_op("power_to_db", fn, (m,))


class MFCC(nn.Layer):
    """Log-mel -> DCT-II cepstral coefficients (reference MFCC)."""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 n_mels=64, f_min=50.0, f_max=None, top_db=None,
                 dtype="float32", **mel_kwargs):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, n_mels=n_mels,
            f_min=f_min, f_max=f_max, top_db=top_db, **mel_kwargs)
        # orthonormal DCT-II basis [n_mfcc, n_mels]
        k = np.arange(n_mfcc)[:, None]
        n = np.arange(n_mels)[None, :]
        basis = np.cos(np.pi / n_mels * (n + 0.5) * k) \
            * np.sqrt(2.0 / n_mels)
        basis[0] *= 1.0 / np.sqrt(2.0)
        self.register_buffer("dct", Tensor(basis.astype(dtype)))

    def forward(self, x):
        lm = self.log_mel(x)
        return run_op("mfcc_dct",
                      lambda a, d: jnp.einsum("km,bmt->bkt", d, a),
                      (lm, self.dct))
