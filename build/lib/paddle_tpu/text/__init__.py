"""``paddle.text`` (reference: `python/paddle/text/__init__.py`):
Viterbi decoding + classic NLP datasets."""

from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401
from .datasets import (  # noqa: F401
    UCIHousing, Imdb, Imikolov, Movielens, WMT16, Conll05st)

__all__ = ["ViterbiDecoder", "viterbi_decode",
           "UCIHousing", "Imdb", "Imikolov", "Movielens", "WMT16", "Conll05st"]
