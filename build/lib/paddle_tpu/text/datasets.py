"""Text datasets (reference: `python/paddle/text/datasets/`).

The reference auto-downloads corpora; this build runs with zero egress,
so every dataset takes ``data_file`` pointing at the same archive the
reference would download (formats identical — an aclImdb tar for
:class:`Imdb`, the simple-examples PTB tar for :class:`Imikolov`, the
whitespace table for :class:`UCIHousing`). Parsing, vocabulary building,
and example layout match the reference classes cited per dataset.
"""

from __future__ import annotations

import collections
import re
import string
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Movielens", "WMT16",
           "Conll05st"]


class UCIHousing(Dataset):
    """Boston-housing regression table (reference
    `text/datasets/uci_housing.py`): 14 whitespace-separated columns,
    features mean-centered and range-normalized over the full table,
    80/20 train/test split."""

    def __init__(self, data_file=None, mode="train", download=False):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        if data_file is None:
            raise ValueError(
                "data_file is required (no network in this build): pass "
                "the housing.data table the reference downloads")
        self.data_file = data_file
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.fromfile(self.data_file, sep=" ")
        data = data.reshape(data.shape[0] // feature_num, feature_num)
        maxs, mins, avgs = (data.max(0), data.min(0),
                            data.sum(0) / data.shape[0])
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return (row[:-1].astype("float32"), row[-1:].astype("float32"))

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment corpus from the aclImdb tar (reference
    `text/datasets/imdb.py`): vocabulary of words with frequency >
    ``cutoff`` over train+test, docs as id arrays, label 0=pos 1=neg."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=False):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        if data_file is None:
            raise ValueError(
                "data_file is required (no network in this build): pass "
                "the aclImdb_v1.tar.gz archive the reference downloads")
        self.data_file = data_file
        self.word_idx = self._build_word_dict(cutoff)
        self._load_anno()

    def _tokenize(self, pattern):
        docs = []
        with tarfile.open(self.data_file) as tarf:
            member = tarf.next()
            while member is not None:
                if pattern.match(member.name):
                    docs.append(
                        tarf.extractfile(member).read()
                        .rstrip(b"\n\r")
                        .translate(None,
                                   string.punctuation.encode("latin-1"))
                        .lower().split())
                member = tarf.next()
        return docs

    def _build_word_dict(self, cutoff):
        freq = collections.defaultdict(int)
        pattern = re.compile(
            r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$")
        for doc in self._tokenize(pattern):
            for w in doc:
                freq[w] += 1
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        # keys are bytes (tar payload); the reference mixes a str '<unk>'
        # into a bytes vocab — uniform bytes here
        word_idx[b"<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        unk = self.word_idx[b"<unk>"]
        self.docs, self.labels = [], []
        for label, sub in ((0, "pos"), (1, "neg")):
            pattern = re.compile(rf"aclImdb/{self.mode}/{sub}/.*\.txt$")
            for doc in self._tokenize(pattern):
                self.docs.append(
                    [self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.array(self.docs[idx]), np.array([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB language-model corpus from the simple-examples tar (reference
    `text/datasets/imikolov.py`): vocabulary over train+valid with
    ``<s>``/``<e>`` markers, examples as N-grams or (src, trg) pairs."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=False):
        if data_type.upper() not in ("NGRAM", "SEQ"):
            raise ValueError(
                f"data_type should be 'NGRAM' or 'SEQ', got {data_type}")
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.data_type = data_type.upper()
        self.mode = mode.lower()
        self.window_size = window_size
        self.min_word_freq = min_word_freq
        if data_file is None:
            raise ValueError(
                "data_file is required (no network in this build): pass "
                "the simple-examples.tgz archive the reference downloads")
        self.data_file = data_file
        self.word_idx = self._build_word_dict(min_word_freq)
        self._load_anno()

    @staticmethod
    def _word_count(f, freq=None):
        freq = freq if freq is not None else collections.defaultdict(int)
        for line in f:
            for w in line.strip().split():
                freq[w] += 1
            freq[b"<s>"] += 1
            freq[b"<e>"] += 1
        return freq

    def _build_word_dict(self, cutoff):
        with tarfile.open(self.data_file) as tf:
            freq = self._word_count(
                tf.extractfile("./simple-examples/data/ptb.valid.txt"),
                self._word_count(
                    tf.extractfile("./simple-examples/data/ptb.train.txt")))
        freq.pop(b"<unk>", None)
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx[b"<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        self.data = []
        unk = self.word_idx[b"<unk>"]
        with tarfile.open(self.data_file) as tf:
            f = tf.extractfile(
                f"./simple-examples/data/ptb.{self.mode}.txt")
            for line in f:
                if self.data_type == "NGRAM":
                    if self.window_size < 0:
                        raise ValueError("NGRAM needs window_size > 0")
                    toks = [b"<s>"] + line.strip().split() + [b"<e>"]
                    if len(toks) < self.window_size:
                        continue
                    ids = [self.word_idx.get(w, unk) for w in toks]
                    for i in range(self.window_size, len(ids) + 1):
                        self.data.append(
                            tuple(ids[i - self.window_size:i]))
                else:
                    ids = [self.word_idx.get(w, unk)
                           for w in line.strip().split()]
                    src = [self.word_idx[b"<s>"]] + ids
                    trg = ids + [self.word_idx[b"<e>"]]
                    if 0 < self.window_size < len(src):
                        continue
                    self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    """Movie metadata row (reference `text/datasets/movielens.py`)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [[self.index],
                [categories_dict[c] for c in self.categories],
                [movie_title_dict[w.lower()] for w in self.title.split()]]


class UserInfo:
    """User metadata row (reference `text/datasets/movielens.py`)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = _AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]


class Movielens(Dataset):
    """MovieLens-1M ratings from the ml-1m.zip archive (reference
    `text/datasets/movielens.py`): '::'-separated users/movies/ratings
    tables, ratings rescaled to [-5, 5] via r*2-5, random train/test
    split by ``test_ratio`` under ``rand_seed``. Each example is
    (uid, gender, age_bucket, job, movie_id, category_ids, title_ids,
    rating)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=False):
        import re
        import zipfile

        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', got {mode}")
        self.mode = mode.lower()
        if data_file is None:
            raise ValueError(
                "data_file is required (no network in this build): pass "
                "the ml-1m.zip archive the reference downloads")
        self.data_file = data_file

        pattern = re.compile(r"^(.*)\((\d+)\)$")
        self.movie_info = {}
        self.user_info = {}
        title_words, category_set = set(), set()
        with zipfile.ZipFile(data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in f:
                    mid, title, cats = line.decode("latin").strip() \
                        .split("::")
                    cats = cats.split("|")
                    category_set.update(cats)
                    m = pattern.match(title)
                    title = m.group(1) if m else title
                    self.movie_info[int(mid)] = MovieInfo(mid, cats, title)
                    title_words.update(w.lower() for w in title.split())
            self.movie_title_dict = {w: i for i, w
                                     in enumerate(sorted(title_words))}
            self.categories_dict = {c: i for i, c
                                    in enumerate(sorted(category_set))}
            with z.open("ml-1m/users.dat") as f:
                for line in f:
                    uid, gender, age, job, _ = line.decode("latin") \
                        .strip().split("::")
                    self.user_info[int(uid)] = UserInfo(uid, gender, age,
                                                        job)
            rng = np.random.RandomState(rand_seed)
            is_test = self.mode == "test"
            self.data = []
            with z.open("ml-1m/ratings.dat") as f:
                for line in f:
                    if (rng.random_sample() < test_ratio) != is_test:
                        continue
                    uid, mid, rating, _ = line.decode("latin").strip() \
                        .split("::")
                    usr = self.user_info[int(uid)]
                    mov = self.movie_info[int(mid)]
                    self.data.append(
                        usr.value()
                        + mov.value(self.categories_dict,
                                    self.movie_title_dict)
                        + [[float(rating) * 2 - 5.0]])

    def __getitem__(self, idx):
        return tuple(np.array(d) for d in self.data[idx])

    def __len__(self):
        return len(self.data)


class WMT16(Dataset):
    """WMT16 en-de parallel corpus from the reference's tar layout
    (reference `text/datasets/wmt16.py`): members ``wmt16/{train,val,
    test}`` hold tab-separated "en\\tde" lines. Per-language vocabularies
    keep the ``dict_size`` most frequent train-set words behind the
    <s>/<e>/<unk> markers (built in memory — the reference caches dict
    files on disk). Examples are (src_ids with <s>...<e>, trg_ids with
    leading <s>, trg_ids_next with trailing <e>)."""

    START, END, UNK = "<s>", "<e>", "<unk>"

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en", download=False):
        if mode.lower() not in ("train", "val", "test"):
            raise ValueError(
                f"mode should be 'train', 'val' or 'test', got {mode}")
        if lang not in ("en", "de"):
            raise ValueError(f"lang should be 'en' or 'de', got {lang}")
        if data_file is None:
            raise ValueError(
                "data_file is required (no network in this build): pass "
                "the wmt16 tar archive the reference downloads")
        self.mode = mode.lower()
        self.lang = lang
        self.data_file = data_file
        self.src_dict = self._build_dict(lang, src_dict_size)
        self.trg_dict = self._build_dict("de" if lang == "en" else "en",
                                         trg_dict_size)
        self._load_data()

    def _build_dict(self, lang, dict_size):
        col = 0 if lang == "en" else 1
        freq = collections.defaultdict(int)
        with tarfile.open(self.data_file) as tf:
            for line in tf.extractfile("wmt16/train"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                for w in parts[col].split():
                    freq[w] += 1
        words = [w for w, _ in sorted(freq.items(),
                                      key=lambda x: (-x[1], x[0]))]
        if dict_size > 0:
            words = words[:max(dict_size - 3, 0)]
        vocab = [self.START, self.END, self.UNK] + words
        return {w: i for i, w in enumerate(vocab)}

    def _load_data(self):
        start = self.src_dict[self.START]
        end = self.src_dict[self.END]
        unk = self.src_dict[self.UNK]
        src_col = 0 if self.lang == "en" else 1
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(self.data_file) as tf:
            for line in tf.extractfile(f"wmt16/{self.mode}"):
                parts = line.decode().strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [self.src_dict.get(w, unk)
                       for w in parts[src_col].split()]
                trg = [self.trg_dict.get(w, unk)
                       for w in parts[1 - src_col].split()]
                self.src_ids.append([start] + src + [end])
                self.trg_ids.append([start] + trg)
                self.trg_ids_next.append(trg + [end])

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def __len__(self):
        return len(self.src_ids)


class Conll05st(Dataset):
    """CoNLL-2005 SRL test split (reference
    `text/datasets/conll05.py`): the tar holds gzipped word and
    proposition columns; each verb of a sentence yields one example with
    the bracketed proposition tags converted to B/I/O and a 5-word
    context window around the predicate. Dict files (word/verb/target)
    are the reference's plain one-entry-per-line files."""

    UNK_IDX = 0

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, emb_file=None,
                 download=False):
        import gzip

        for name, f in (("data_file", data_file),
                        ("word_dict_file", word_dict_file),
                        ("verb_dict_file", verb_dict_file),
                        ("target_dict_file", target_dict_file)):
            if f is None:
                raise ValueError(
                    f"{name} is required (no network in this build): pass "
                    "the conll05st files the reference downloads")
        self.data_file = data_file
        self.word_dict = self._load_dict(word_dict_file)
        self.predicate_dict = self._load_dict(verb_dict_file)
        self.label_dict = self._load_label_dict(target_dict_file)
        self.emb_file = emb_file

        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(data_file) as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words, \
                    gzip.GzipFile(fileobj=pf) as props:
                self._parse(words, props)

    @staticmethod
    def _load_dict(filename):
        with open(filename) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _load_label_dict(filename):
        tags = set()
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if line.startswith(("B-", "I-")):
                    tags.add(line[2:])
        d, idx = {}, 0
        for tag in tags:
            d["B-" + tag] = idx
            d["I-" + tag] = idx + 1
            idx += 2
        d["O"] = idx
        return d

    def _parse(self, words_file, props_file):
        # lockstep: one word line per prop line; a blank prop line ends
        # the sentence (the reference's protocol)
        sentence, columns = [], []
        for word, prop in zip(words_file, props_file):
            word = word.strip().decode()
            prop = prop.strip().decode().split()
            if not prop:
                self._finish_sentence(sentence, columns)
                sentence, columns = [], []
            else:
                sentence.append(word)
                columns.append(prop)
        if sentence:
            self._finish_sentence(sentence, columns)

    def _finish_sentence(self, sentence, columns):
        if not columns:
            return
        # transpose the per-token rows into per-column tag sequences
        per_col = [[row[i] for row in columns]
                   for i in range(len(columns[0]))]
        verbs = [v for v in per_col[0] if v != "-"]
        for i, col in enumerate(per_col[1:]):
            seq, cur, inside = [], "O", False
            for tag in col:
                if tag == "*":
                    seq.append("I-" + cur if inside else "O")
                elif tag == "*)":
                    seq.append("I-" + cur)
                    inside = False
                elif "(" in tag and ")" in tag:
                    cur = tag[1:tag.find("*")]
                    seq.append("B-" + cur)
                    inside = False
                elif "(" in tag:
                    cur = tag[1:tag.find("*")]
                    seq.append("B-" + cur)
                    inside = True
                else:
                    raise ValueError(f"unexpected proposition tag {tag!r}")
            self.sentences.append(list(sentence))
            self.predicates.append(verbs[i])
            self.labels.append(seq)

    def __getitem__(self, idx):
        sentence = self.sentences[idx]
        labels = self.labels[idx]
        n = len(sentence)
        v = labels.index("B-V")
        mark = [0] * n
        ctx = {}
        for off, name, fallback in ((-2, "n2", "bos"), (-1, "n1", "bos"),
                                    (0, "0", None), (1, "p1", "eos"),
                                    (2, "p2", "eos")):
            j = v + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[name] = sentence[j]
            else:
                ctx[name] = fallback
        wd = self.word_dict
        word_idx = [wd.get(w, self.UNK_IDX) for w in sentence]
        rows = [word_idx]
        for name in ("n2", "n1", "0", "p1", "p2"):
            rows.append([wd.get(ctx[name], self.UNK_IDX)] * n)
        rows.append([self.predicate_dict.get(self.predicates[idx])] * n)
        rows.append(mark)
        rows.append([self.label_dict.get(t) for t in labels])
        return tuple(np.array(r) for r in rows)

    def __len__(self):
        return len(self.sentences)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict
