"""Viterbi decoding (reference: `python/paddle/text/viterbi_decode.py`).

TPU-native: the forward max-product recursion is a ``lax.scan`` over
time with the [B, N, N] score expansion on the VPU; backtrace is a
second reversed scan over the stored backpointers. Variable lengths are
handled by masking (frozen alpha beyond each sequence's end), keeping
everything static-shaped for jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor, run_op

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi(pot, trans, lengths, include_bos_eos_tag):
    b, l, n = pot.shape
    lengths = lengths.astype(jnp.int32)
    alpha0 = pot[:, 0, :]
    if include_bos_eos_tag:
        # last row/col = start tag, second-to-last = stop tag
        alpha0 = alpha0 + trans[-1][None, :]

    def step(alpha, xs):
        pot_t, t = xs
        scores = alpha[:, :, None] + trans[None]          # [B, N, N]
        best = jnp.max(scores, axis=1) + pot_t            # [B, N]
        bp = jnp.argmax(scores, axis=1).astype(jnp.int32)
        live = (t < lengths)[:, None]
        return jnp.where(live, best, alpha), bp

    ts = jnp.arange(1, l, dtype=jnp.int32)
    alpha, bps = jax.lax.scan(step, alpha0,
                              (jnp.swapaxes(pot[:, 1:], 0, 1), ts))
    final = alpha + (trans[:, -2][None] if include_bos_eos_tag else 0.0)
    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1).astype(jnp.int32)

    def back(tag, xs):
        bp_t, t = xs
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        # the transition into position t+1 only happened if t+1 < length
        tag = jnp.where(t + 1 <= lengths - 1, prev, tag)
        return tag, tag

    ts_rev = jnp.arange(l - 2, -1, -1, dtype=jnp.int32)
    _, tags_rev = jax.lax.scan(back, last_tag, (bps[::-1], ts_rev))
    paths = jnp.concatenate(
        [tags_rev[::-1], last_tag[None]], axis=0).swapaxes(0, 1)  # [B, L]
    pos = jnp.arange(l, dtype=jnp.int32)[None, :]
    paths = jnp.where(pos < lengths[:, None], paths, 0)
    return scores, paths.astype(jnp.int32)


from ..tensor.registry import defop


@defop(name="viterbi_decode", differentiable=False)
def _viterbi_op(potentials, transition_params, lengths,
                include_bos_eos_tag=True):
    """Schema entry for the reference op `viterbi_decode`
    (`phi/kernels/cpu/viterbi_decode_kernel.cc`)."""
    return _viterbi(potentials, transition_params, lengths,
                    include_bos_eos_tag)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag sequence per batch row.

    Returns ``(scores [B], paths [B, max(lengths)])`` — like the
    reference, the path tensor is truncated to the longest real
    sequence; shorter rows are zero-padded.
    """
    scores, paths = _viterbi_op(potentials, transition_params, lengths,
                                include_bos_eos_tag=include_bos_eos_tag)
    max_len = int(np.asarray(
        getattr(lengths, "_data", lengths)).max())
    return scores, paths[:, :max_len]


class ViterbiDecoder:
    """Layer-style wrapper (reference ``ViterbiDecoder``)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(transitions))
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
