"""Comparison & logical ops (reference: `python/paddle/tensor/logic.py`)."""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from .registry import defop

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose",
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift", "is_empty", "is_tensor",
]


def _cmp(name, fn):
    @defop(name=name, method=True, differentiable=False)
    def op(x, y):
        return fn(x, jnp.asarray(y))
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)
bitwise_left_shift = _cmp("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _cmp("bitwise_right_shift", jnp.right_shift)


@defop(method=True, differentiable=False)
def logical_not(x):
    return jnp.logical_not(x)


@defop(method=True, differentiable=False)
def bitwise_not(x):
    return jnp.bitwise_not(x)


@defop(method=True, differentiable=False)
def equal_all(x, y):
    return jnp.array_equal(x, y)


@defop(method=True, differentiable=False)
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=float(rtol), atol=float(atol), equal_nan=equal_nan)


@defop(method=True, differentiable=False)
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=float(rtol), atol=float(atol), equal_nan=equal_nan)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
