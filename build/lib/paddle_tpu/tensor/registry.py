"""Single-source op registry.

The reference keeps ~600 op schemas in YAML (`paddle/phi/api/yaml/ops.yaml`)
and generates the C++ API, autograd nodes, and Python bindings from them
(SURVEY §2.2). Here the single source is the decorated jax-level function:
``@defop`` registers it, wraps it with the autograd executor
(`framework.tensor.run_op` — grad comes from ``jax.vjp``, no per-op grad
rules), and optionally attaches it as a ``Tensor`` method. ``OPS`` is the
machine-readable inventory (the analog of the YAML file).
"""

from __future__ import annotations

import functools

from ..framework.tensor import Tensor, run_op

__all__ = ["defop", "OPS", "attach_tensor_method"]

# name -> {fn, wrapper, differentiable, methods}
OPS: dict[str, dict] = {}


def defop(name=None, differentiable=True, method=False, method_name=None,
          inplace_method=None):
    """Register an op.

    Args:
        name: public op name (defaults to fn.__name__).
        differentiable: record a grad node for this op.
        method: also attach as ``Tensor.<name>`` method.
        method_name: method name if different from op name.
        inplace_method: if set, also attach ``Tensor.<inplace_method>`` that
            rebinds the tensor payload in place (paddle's ``op_`` convention).
    """
    def deco(fn):
        opname = name or fn.__name__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            kwargs.pop("name", None)
            return run_op(opname, fn, args, kwargs, differentiable=differentiable)

        OPS[opname] = {"fn": fn, "wrapper": wrapper,
                       "differentiable": differentiable,
                       "method": (method_name or opname) if method else None,
                       "inplace": inplace_method,
                       "module": fn.__module__}
        if method:
            attach_tensor_method(method_name or opname, wrapper)
        if inplace_method:
            def inplace(self, *args, **kwargs):
                out = wrapper(self, *args, **kwargs)
                self._data = out._data
                self._node = out._node
                self._out_index = out._out_index
                self.stop_gradient = out.stop_gradient
                return self
            attach_tensor_method(inplace_method, inplace)
        return wrapper
    return deco


def attach_tensor_method(name, fn):
    """Attach a function as a Tensor method (reference:
    ``python/paddle/base/dygraph/math_op_patch.py`` monkey-patching)."""
    if getattr(fn, "__self_is_first_arg__", True):
        setattr(Tensor, name, fn)


def register_existing(fn, name, differentiable=True):
    """Inventory an EXISTING public function as a schema op.

    Some reference ops (`concat`, `topk`, creation/random ops, ...) are
    implemented here as plain functions wrapping ``run_op`` directly —
    variadic inputs or eager RNG handling don't fit the ``@defop``
    template. They are still ops of the framework; this records them in
    ``OPS`` (and therefore in ops.yaml and ``_C_ops``) with the public
    function as the dispatch target."""
    OPS[name] = {"fn": fn, "wrapper": fn, "differentiable": differentiable,
                 "method": None, "inplace": None, "module": fn.__module__}
    return fn
