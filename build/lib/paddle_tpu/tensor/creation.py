"""Tensor creation ops (reference: `python/paddle/tensor/creation.py`)."""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework.tensor import Tensor, to_tensor, run_op
from .registry import defop

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "diag", "diagflat", "assign", "tril", "triu", "meshgrid", "clone",
    "complex", "polar", "tril_indices", "triu_indices", "one_hot",
    "fill"]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else (default or dtypes.get_default_dtype())


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        dtype = jnp.result_type(fill_value) if not isinstance(fill_value, float) \
            else dtypes.get_default_dtype()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(x._data if isinstance(x, Tensor) else x,
                                 dtype=dtypes.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(x._data if isinstance(x, Tensor) else x,
                                dtype=dtypes.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(x._data if isinstance(x, Tensor) else x,
                                fill_value, dtype=dtypes.convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    return Tensor(jnp.arange(start, end, step, dtype=dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(val(start), val(stop), int(val(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.logspace(val(start), val(stop), int(val(num)),
                               base=val(base), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


@defop(method=True)
def diag(x, offset=0, padding_value=0):
    if padding_value != 0:
        d = jnp.diag(x, k=offset)
        if x.ndim == 1:
            n = x.shape[0] + abs(offset)
            full_mat = jnp.full((n, n), padding_value, dtype=x.dtype)
            idx = jnp.arange(x.shape[0])
            r = idx if offset >= 0 else idx - offset
            c = idx + offset if offset >= 0 else idx
            return full_mat.at[r, c].set(x)
        return d
    return jnp.diag(x, k=offset)


@defop()
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


@defop(method=True)
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@defop(method=True)
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@defop()
def assign(x, output=None):
    return jnp.asarray(x)


def clone(x, name=None):
    return assign(x)


def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    arrays = [a._data if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
    return run_op("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")),
                  [Tensor(a) for a in arrays])


@defop(name="complex")
def complex(real, imag):
    return real + 1j * imag


@defop()
def polar(abs, angle):
    return abs * jnp.cos(angle) + 1j * abs * jnp.sin(angle)


def tril_indices(row, col=None, offset=0, dtype=None):
    col = row if col is None else col
    r, c = np.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=dtypes.convert_dtype(dtype or "int64")))


def triu_indices(row, col=None, offset=0, dtype=None):
    col = row if col is None else col
    r, c = np.triu_indices(row, k=offset, m=col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=dtypes.convert_dtype(dtype or "int64")))


@defop(differentiable=False)
def one_hot(x, num_classes):
    return jnp.eye(num_classes, dtype=dtypes.get_default_dtype())[x]


@defop(method=True, inplace_method="fill_")
def fill(x, value):
    """Fill the whole tensor with ``value`` (reference op `fill`; the
    in-place spelling is ``Tensor.fill_``)."""
    return jnp.full_like(x, value)
