"""Tensor op surface — re-exports every op and patches Tensor operators.

Reference analog: `python/paddle/tensor/__init__.py` plus the operator
monkey-patching in `python/paddle/base/dygraph/math_op_patch.py`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from .registry import OPS  # noqa: F401
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .attribute import *  # noqa: F401,F403

from . import math as _math
from . import logic as _logic
from . import manipulation as _manip

# ---------------------------------------------------------------------------
# operator overloads
# ---------------------------------------------------------------------------
def _swap(fn):
    return lambda self, other: fn(_coerce(other, self), self)


def _coerce(v, like):
    if isinstance(v, Tensor):
        return v
    return Tensor(jnp.asarray(v))


def _binop(fn):
    def op(self, other):
        return fn(self, other if isinstance(other, Tensor) else _coerce(other, self))
    return op


Tensor.__add__ = _binop(_math.add)
Tensor.__radd__ = _swap(_math.add)
Tensor.__sub__ = _binop(_math.subtract)
Tensor.__rsub__ = _swap(_math.subtract)
Tensor.__mul__ = _binop(_math.multiply)
Tensor.__rmul__ = _swap(_math.multiply)
Tensor.__truediv__ = _binop(_math.divide)
Tensor.__rtruediv__ = _swap(_math.divide)
Tensor.__floordiv__ = _binop(_math.floor_divide)
Tensor.__rfloordiv__ = _swap(_math.floor_divide)
Tensor.__mod__ = _binop(_math.mod)
Tensor.__rmod__ = _swap(_math.mod)
Tensor.__pow__ = _binop(_math.pow)
Tensor.__rpow__ = _swap(_math.pow)
Tensor.__matmul__ = _binop(matmul)
Tensor.__rmatmul__ = _swap(matmul)
Tensor.__neg__ = lambda self: _math.neg(self)
Tensor.__abs__ = lambda self: _math.abs(self)
Tensor.__invert__ = lambda self: _logic.logical_not(self) \
    if self.dtype == jnp.bool_ else _logic.bitwise_not(self)

Tensor.__eq__ = _binop(_logic.equal)
Tensor.__ne__ = _binop(_logic.not_equal)
Tensor.__lt__ = _binop(_logic.less_than)
Tensor.__le__ = _binop(_logic.less_equal)
Tensor.__gt__ = _binop(_logic.greater_than)
Tensor.__ge__ = _binop(_logic.greater_equal)
Tensor.__and__ = _binop(lambda a, b: _logic.logical_and(a, b)
                        if a.dtype == jnp.bool_ else _logic.bitwise_and(a, b))
Tensor.__or__ = _binop(lambda a, b: _logic.logical_or(a, b)
                       if a.dtype == jnp.bool_ else _logic.bitwise_or(a, b))
Tensor.__xor__ = _binop(lambda a, b: _logic.logical_xor(a, b)
                        if a.dtype == jnp.bool_ else _logic.bitwise_xor(a, b))
Tensor.__lshift__ = _binop(_logic.bitwise_left_shift)
Tensor.__rshift__ = _binop(_logic.bitwise_right_shift)

# in-place arithmetic: rebind payload (optimizers rely on these)
def _iop(fn):
    def op(self, other):
        out = fn(self, other if isinstance(other, Tensor) else _coerce(other, self))
        self._data, self._node, self._out_index = out._data, out._node, out._out_index
        self.stop_gradient = out.stop_gradient and self.stop_gradient
        return self
    return op


Tensor.__iadd__ = _iop(_math.add)
Tensor.__isub__ = _iop(_math.subtract)
Tensor.__imul__ = _iop(_math.multiply)
Tensor.__itruediv__ = _iop(_math.divide)

Tensor.add_ = _iop(_math.add)
Tensor.subtract_ = _iop(_math.subtract)
Tensor.multiply_ = _iop(_math.multiply)
Tensor.divide_ = _iop(_math.divide)
Tensor.scale_ = lambda self, scale=1.0, bias=0.0, **kw: _iop(
    lambda a, b: _math.add(_math.multiply(a, b), Tensor(jnp.asarray(bias))))(self, scale)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    from ..framework.tensor import run_op
    s = scale._data if isinstance(scale, Tensor) else scale
    if bias_after_scale:
        out = run_op("scale", lambda a: a * s + bias, [x])
    else:
        out = run_op("scale", lambda a: (a + bias) * s, [x])
    return out


Tensor.scale = scale
Tensor.mean = _math.mean
Tensor.item = Tensor.item  # keep

__all__ = [  # noqa: F405
    name for name in dir() if not name.startswith("_")
]
