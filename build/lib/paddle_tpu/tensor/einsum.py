"""Einsum (reference: `python/paddle/tensor/einsum.py` — here a direct
lowering to XLA's native einsum, which maps contractions onto the MXU)."""

from __future__ import annotations

import jax.numpy as jnp

from .registry import defop

__all__ = ["einsum"]


@defop(name="einsum")
def _einsum_impl(equation, *operands):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands, name=None):
    return _einsum_impl(equation, *operands)
