"""Random sampling ops (reference: `python/paddle/tensor/random.py`).

All draws go through ``framework.random.next_key()`` so they respect the
active RNG scope (global generator eagerly; traced key under jit).
"""

from __future__ import annotations

from ..framework.dtype import default_int as _i64

import jax
import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework import random as framework_random
from ..framework.tensor import Tensor, run_op

__all__ = [
    "rand", "randn", "randint", "randint_like", "uniform", "normal",
    "standard_normal", "gaussian", "randperm", "bernoulli", "multinomial",
    "poisson", "exponential_", "uniform_", "normal_", "shuffle", "binomial",
    "log_normal", "standard_gamma",
    "truncated_gaussian_random", "dirichlet",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else dtypes.get_default_dtype()


def rand(shape, dtype=None, name=None):
    key = framework_random.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=_dt(dtype)))


def randn(shape, dtype=None, name=None):
    key = framework_random.next_key()
    return Tensor(jax.random.normal(key, _shape(shape), dtype=_dt(dtype)))


standard_normal = randn


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = jax.random.key(seed) if seed else framework_random.next_key()
    return Tensor(mean + std * jax.random.normal(key, _shape(shape), dtype=_dt(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = framework_random.next_key()
    return Tensor(jax.random.randint(key, _shape(shape), low, high,
                                     dtype=dtypes.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    key = framework_random.next_key()
    dt = dtypes.convert_dtype(dtype) if dtype is not None else x.dtype
    out = jax.random.randint(key, tuple(x.shape), low, high, dtype=_i64())
    return Tensor(out.astype(dt))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else framework_random.next_key()
    mn = min.item() if isinstance(min, Tensor) else min
    mx = max.item() if isinstance(max, Tensor) else max
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=_dt(dtype),
                                     minval=mn, maxval=mx))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    key = framework_random.next_key()
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        out_shape = jnp.broadcast_shapes(
            jnp.shape(m), jnp.shape(s)) if shape is None else _shape(shape)
        return Tensor(m + s * jax.random.normal(key, out_shape,
                                                dtype=dtypes.get_default_dtype()))
    if shape is None:
        shape = (1,)
    return Tensor(mean + std * jax.random.normal(key, _shape(shape),
                                                 dtype=dtypes.get_default_dtype()))


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    n = normal(mean, std, shape)
    return Tensor(jnp.exp(n._data))


def randperm(n, dtype="int64", name=None):
    key = framework_random.next_key()
    return Tensor(jax.random.permutation(key, n).astype(dtypes.convert_dtype(dtype)))


def bernoulli(x, p=None, name=None):
    key = framework_random.next_key()
    probs = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    u = jax.random.uniform(key, jnp.shape(probs))
    return Tensor((u < probs).astype(probs.dtype if jnp.issubdtype(
        probs.dtype, jnp.floating) else jnp.float32))


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = framework_random.next_key()
    probs = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    if probs.ndim == 1:
        out = jax.random.choice(key, probs.shape[0], shape=(num_samples,),
                                replace=replacement, p=probs / probs.sum())
        return Tensor(out.astype(_i64()))
    keys = jax.random.split(key, probs.shape[0])
    outs = []
    for i in range(probs.shape[0]):
        outs.append(jax.random.choice(
            keys[i], probs.shape[1], shape=(num_samples,), replace=replacement,
            p=probs[i] / probs[i].sum()))
    return Tensor(jnp.stack(outs).astype(_i64()))


def poisson(x, name=None):
    key = framework_random.next_key()
    lam = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(key, lam).astype(lam.dtype))


def binomial(count, prob, name=None):
    key = framework_random.next_key()
    n = count._data if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._data if isinstance(prob, Tensor) else jnp.asarray(prob)
    return Tensor(jax.random.binomial(key, n.astype(jnp.float32), p).astype(_i64()))


def standard_gamma(x, name=None):
    key = framework_random.next_key()
    alpha = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.gamma(key, alpha))


def exponential_(x, lam=1.0, name=None):
    key = framework_random.next_key()
    u = jax.random.uniform(key, tuple(x.shape), dtype=x.dtype if jnp.issubdtype(
        x.dtype, jnp.floating) else jnp.float32)
    x._data = -jnp.log(1.0 - u) / lam
    return x


def uniform_(x, min=-1.0, max=1.0, name=None):
    key = framework_random.next_key()
    x._data = jax.random.uniform(key, tuple(x.shape), dtype=x.dtype,
                                 minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    key = framework_random.next_key()
    x._data = mean + std * jax.random.normal(key, tuple(x.shape), dtype=x.dtype)
    return x


def shuffle(x, name=None):
    key = framework_random.next_key()
    perm = jax.random.permutation(key, x.shape[0])
    from . import manipulation
    return manipulation.index_select(x, Tensor(perm), axis=0)


def truncated_gaussian_random(shape, mean=0.0, std=1.0, dtype=None, a=-2.0,
                              b=2.0, name=None):
    """Gaussian truncated to [a, b] std units (reference op
    `truncated_gaussian_random` — the TruncatedNormal initializer's
    kernel)."""
    import jax

    key = framework_random.next_key()

    def fn(key):
        z = jax.random.truncated_normal(key, a, b, _shape(shape))
        return (z * std + mean).astype(_dt(dtype))

    return run_op("truncated_gaussian_random", fn, (key,),
                  differentiable=False)


def dirichlet(alpha, name=None):
    """Sample from Dirichlet(alpha) (reference op `dirichlet`,
    `phi/kernels/gpu/dirichlet_kernel.cu`): normalized standard-gamma
    draws along the last axis."""
    import jax

    key = framework_random.next_key()

    def fn(alpha, key):
        g = jax.random.gamma(key, alpha)
        return g / jnp.sum(g, axis=-1, keepdims=True)

    return run_op("dirichlet", fn, (alpha, key), differentiable=False)
