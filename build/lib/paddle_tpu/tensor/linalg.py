"""Linear algebra ops (reference: `python/paddle/tensor/linalg.py`).

``matmul`` is the MXU workhorse: it lowers straight to ``jnp.matmul`` →
XLA dot_general, which XLA tiles onto the 128×128 systolic array. The
reference routes this through cuBLAS (`phi/kernels/gpu/matmul_kernel.cu`).
"""

from __future__ import annotations

from ..framework.dtype import default_int as _i64

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from .registry import defop

__all__ = [
    "lu_unpack",
    "matmul", "mm", "bmm", "dot", "mv", "t", "norm", "dist", "cross",
    "cholesky", "cholesky_solve", "qr", "svd", "pca_lowrank", "eig", "eigh",
    "eigvals", "eigvalsh", "det", "slogdet", "inv", "pinv", "solve",
    "triangular_solve", "lstsq", "lu", "matrix_power", "matrix_rank",
    "multi_dot", "histogram", "histogramdd", "bincount", "cov", "corrcoef",
    "cdist", "householder_product", "matrix_exp",
]


@defop(method=True)
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@defop(method=True)
def mm(input, mat2):
    return jnp.matmul(input, mat2)


@defop(method=True)
def bmm(x, y):
    return jnp.matmul(x, y)


@defop(method=True)
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@defop()
def mv(x, vec):
    return jnp.matmul(x, vec)


@defop(method=True)
def t(input):
    if input.ndim <= 1:
        return input
    return jnp.swapaxes(input, -1, -2)


@defop(method=True)
def norm(x, p=None, axis=None, keepdim=False):
    if axis is None and p is None:
        return jnp.linalg.norm(x.reshape(-1))
    if p is None:
        p = 2
    if isinstance(p, str) and p in ("fro", "nuc"):
        return jnp.linalg.norm(x, ord=p, axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis,
                               keepdims=keepdim)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if isinstance(axis, (list, tuple)) and len(axis) == 1:
        axis = axis[0]
    if isinstance(axis, (list, tuple)):
        return jnp.linalg.norm(x, ord=p, axis=tuple(axis), keepdims=keepdim)
    p = float(p)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@defop()
def dist(x, y, p=2.0):
    d = x - y
    p = float(p)
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


@defop()
def cross(x, y, axis=9):
    ax = axis if axis != 9 else None
    if ax is None:
        for i, s in enumerate(x.shape):
            if s == 3:
                ax = i
                break
    return jnp.cross(x, y, axis=ax)


@defop(method=True)
def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


@defop()
def cholesky_solve(x, y, upper=False):
    L = jnp.swapaxes(y, -1, -2).conj() if upper else y
    z = jax.scipy.linalg.solve_triangular(L, x, lower=True)
    return jax.scipy.linalg.solve_triangular(jnp.swapaxes(L, -1, -2).conj(), z, lower=False)


@defop()
def qr(x, mode="reduced"):
    return tuple(jnp.linalg.qr(x, mode=mode)) if mode != "r" \
        else (jnp.linalg.qr(x, mode="r"),)


@defop()
def svd(x, full_matrices=False):
    return tuple(jnp.linalg.svd(x, full_matrices=full_matrices))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    from ..framework.tensor import run_op
    if q is None:
        q = min(6, x.shape[-2], x.shape[-1])

    def fn(a):
        if center:
            a = a - jnp.mean(a, axis=-2, keepdims=True)
        u, s, vt = jnp.linalg.svd(a, full_matrices=False)
        return u[..., :q], s[..., :q], jnp.swapaxes(vt, -1, -2)[..., :q]
    return run_op("pca_lowrank", fn, [x])


@defop(differentiable=False)
def eig(x):
    return tuple(jnp.linalg.eig(x))


@defop()
def eigh(x, UPLO="L"):
    return tuple(jnp.linalg.eigh(x, UPLO=UPLO))


@defop(differentiable=False)
def eigvals(x):
    return jnp.linalg.eigvals(x)


@defop()
def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@defop()
def det(x):
    return jnp.linalg.det(x)


@defop()
def slogdet(x):
    s, ld = jnp.linalg.slogdet(x)
    return jnp.stack([s, ld]) if s.ndim == 0 else jnp.stack([s, ld])


@defop(method=True)
def inv(x):
    return jnp.linalg.inv(x)


@defop()
def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@defop()
def solve(x, y):
    return jnp.linalg.solve(x, y)


@defop()
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    a = x
    if transpose:
        a = jnp.swapaxes(a, -1, -2)
        upper = not upper
    return jax.scipy.linalg.solve_triangular(
        a, y, lower=not upper, unit_diagonal=unitriangular)


@defop(differentiable=False)
def lstsq(x, y, rcond=None, driver=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@defop(differentiable=False)
def lu(x, pivot=True):
    lu_mat, piv = jax.scipy.linalg.lu_factor(x)
    return lu_mat, piv.astype(jnp.int32) + 1  # paddle returns 1-based pivots


@defop()
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@defop(differentiable=False)
def matrix_rank(x, tol=None, hermitian=False):
    return jnp.linalg.matrix_rank(x, rtol=tol)


def multi_dot(x, name=None):
    from ..framework.tensor import run_op
    return run_op("multi_dot", lambda *xs: jnp.linalg.multi_dot(list(xs)), list(x))


@defop(differentiable=False)
def histogram(input, bins=100, min=0, max=0, weight=None, density=False):
    rng = None if (min == 0 and max == 0) else (min, max)
    hist, _ = jnp.histogram(input.reshape(-1), bins=bins, range=rng,
                            weights=None if weight is None else weight.reshape(-1),
                            density=density)
    return hist if density else hist.astype(_i64())


@defop(differentiable=False)
def histogramdd(x, bins=10, ranges=None, density=False, weights=None):
    hist, edges = jnp.histogramdd(x, bins=bins, range=ranges, density=density,
                                  weights=weights)
    return (hist,) + tuple(edges)


@defop(differentiable=False)
def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x.reshape(-1), weights=weights, minlength=minlength,
                        length=None)


@defop()
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@defop()
def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


@defop()
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary"):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


@defop()
def householder_product(x, tau):
    m, n = x.shape[-2], x.shape[-1]
    eye = jnp.eye(m, dtype=x.dtype)

    def body(q, i):
        v = jnp.where(jnp.arange(m) < i, 0.0, x[..., :, i].at[i].set(1.0))
        h = eye - tau[..., i] * jnp.outer(v, v)
        return q @ h, None

    q0 = jnp.eye(m, dtype=x.dtype)
    q, _ = jax.lax.scan(body, q0, jnp.arange(n))
    return q[..., :, :n]


@defop()
def matrix_exp(x):
    return jax.scipy.linalg.expm(x)


@defop()
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True):
    """Unpack jax lu_factor output into (P, L, U) (reference
    `tensor/linalg.py:lu_unpack`; ``y`` is the 1-based pivot vector that
    :func:`lu` returns)."""
    m, n = x.shape[-2], x.shape[-1]
    k = min(m, n)
    if unpack_ludata:
        tri_l = jnp.tril(x[..., :, :k], k=-1)
        eye = jnp.eye(m, k, dtype=x.dtype)
        l_mat = tri_l + eye
        u_mat = jnp.triu(x[..., :k, :])
    else:
        l_mat = u_mat = jnp.zeros((0,), x.dtype)
    if unpack_pivots:
        piv = jnp.asarray(y, jnp.int32) - 1           # back to 0-based
        perm = jnp.arange(m, dtype=jnp.int32)

        def swap(i, p):
            j = piv[..., i]
            pi, pj = p[..., i], p[j]
            p = p.at[..., i].set(pj)
            return p.at[j].set(pi)

        for i in range(piv.shape[-1]):   # pivot count is static
            perm = swap(i, perm)
        p_mat = jnp.eye(m, dtype=x.dtype)[perm].T
    else:
        p_mat = jnp.zeros((0,), x.dtype)
    return p_mat, l_mat, u_mat
