"""Statistics ops (reference: `python/paddle/tensor/stat.py`)."""

from __future__ import annotations

import jax.numpy as jnp

from .registry import defop

__all__ = ["std", "var", "median", "nanmedian", "quantile", "nanquantile"]


def _ax(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@defop(method=True)
def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=_ax(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@defop(method=True)
def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=_ax(axis), ddof=1 if unbiased else 0, keepdims=keepdim)


@defop(method=True)
def median(x, axis=None, keepdim=False, mode="avg"):
    if mode == "min":
        n = x.shape[_ax(axis)] if axis is not None else x.size
        q = jnp.quantile(x, 0.5, axis=_ax(axis), keepdims=keepdim, method="lower") \
            if n % 2 == 0 else jnp.quantile(x, 0.5, axis=_ax(axis), keepdims=keepdim,
                                            method="nearest")
        return q
    return jnp.median(x, axis=_ax(axis), keepdims=keepdim)


@defop()
def nanmedian(x, axis=None, keepdim=False, mode="avg"):
    return jnp.nanmedian(x, axis=_ax(axis), keepdims=keepdim)


@defop()
def quantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.quantile(x, jnp.asarray(q), axis=_ax(axis), keepdims=keepdim,
                        method=interpolation)


@defop()
def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear"):
    return jnp.nanquantile(x, jnp.asarray(q), axis=_ax(axis), keepdims=keepdim,
                           method=interpolation)
