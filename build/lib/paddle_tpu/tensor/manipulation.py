"""Shape / layout / indexing ops (reference: `python/paddle/tensor/manipulation.py`)."""

from __future__ import annotations

import builtins

import numpy as np
import jax
import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework.tensor import Tensor, run_op
from .registry import defop

__all__ = [
    "reshape", "reshape_", "flatten", "transpose", "moveaxis", "swapaxes",
    "concat", "stack", "vstack", "hstack", "dstack", "split", "vsplit",
    "hsplit", "dsplit", "chunk", "squeeze", "unsqueeze", "unsqueeze_",
    "expand", "expand_as", "broadcast_to", "broadcast_tensors", "tile",
    "cast", "slice", "strided_slice", "gather", "gather_nd", "scatter",
    "scatter_nd", "scatter_nd_add", "index_select", "index_add", "index_put",
    "masked_select", "masked_fill", "masked_scatter", "where", "take_along_axis",
    "index_fill",
    "put_along_axis", "flip", "rot90", "roll", "unique", "unique_consecutive",
    "unbind", "unstack", "repeat_interleave", "as_strided", "view", "view_as",
    "tensordot", "crop", "pad", "shard_index", "tolist", "as_complex",
    "as_real", "atleast_1d", "atleast_2d", "atleast_3d", "diagonal",
    "diagonal_scatter", "select_scatter", "slice_scatter", "unflatten",
    "unfold", "tensor_split",
    "diag_embed", "fill_diagonal", "fill_diagonal_tensor", "multiplex",
    "reverse", "sequence_mask", "shuffle_channel", "temporal_shift",
    "gather_tree",
]


def _axes(a):
    if isinstance(a, Tensor):
        return tuple(int(v) for v in np.asarray(a.numpy()).reshape(-1))
    if isinstance(a, (list, tuple)):
        return tuple(int(x._data) if isinstance(x, Tensor) else int(x) for x in a)
    return int(a)


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(v) for v in shape.numpy())
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


@defop(method=True)
def reshape(x, shape):
    return jnp.reshape(x, _shape_arg(shape) if not isinstance(shape, int) else (shape,))


def reshape_(x, shape):
    out = reshape(x, shape)
    x._data, x._node, x._out_index = out._data, out._node, out._out_index
    return x


@defop(method=True)
def flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return jnp.reshape(x, (1,))
    start = start_axis % nd
    stop = stop_axis % nd
    new_shape = x.shape[:start] + (-1,) + x.shape[stop + 1:]
    return jnp.reshape(x, new_shape)


@defop(method=True)
def transpose(x, perm=None):
    return jnp.transpose(x, axes=_axes(perm) if perm is not None else None)


@defop()
def moveaxis(x, source, destination):
    return jnp.moveaxis(x, _axes(source), _axes(destination))


@defop()
def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


def concat(x, axis=0, name=None):
    axis = _axes(axis)
    return run_op("concat", lambda *xs: jnp.concatenate(
        [jnp.asarray(a) for a in xs], axis=axis), list(x))


def stack(x, axis=0, name=None):
    return run_op("stack", lambda *xs: jnp.stack(
        [jnp.asarray(a) for a in xs], axis=axis), list(x))


def vstack(x, name=None):
    return run_op("vstack", lambda *xs: jnp.vstack(list(xs)), list(x))


def hstack(x, name=None):
    return run_op("hstack", lambda *xs: jnp.hstack(list(xs)), list(x))


def dstack(x, name=None):
    return run_op("dstack", lambda *xs: jnp.dstack(list(xs)), list(x))


@defop(method=True)
def split(x, num_or_sections, axis=0):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return tuple(jnp.split(x, num_or_sections, axis=axis))
    secs = [int(s._data) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
    total = x.shape[axis]
    known = sum(s for s in secs if s >= 0)
    secs = [s if s >= 0 else total - known for s in secs]
    idx = np.cumsum(secs)[:-1]
    return tuple(jnp.split(x, idx, axis=axis))


def vsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=0)


def hsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=1)


def dsplit(x, num_or_sections, name=None):
    return split(x, num_or_sections, axis=2)


@defop()
def tensor_split(x, num_or_indices, axis=0):
    if isinstance(num_or_indices, int):
        return tuple(jnp.array_split(x, num_or_indices, axis=int(axis)))
    return tuple(jnp.split(x, list(num_or_indices), axis=int(axis)))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


@defop(method=True)
def squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    ax = _axes(axis)
    if isinstance(ax, int):
        ax = (ax,)
    ax = tuple(a for a in ax if x.shape[a] == 1)
    return jnp.squeeze(x, axis=ax) if ax else x


@defop(method=True)
def unsqueeze(x, axis):
    ax = _axes(axis)
    if isinstance(ax, int):
        ax = (ax,)
    out = x
    for a in sorted(a % (out.ndim + 1) for a in ax):
        out = jnp.expand_dims(out, a)
    return out


def unsqueeze_(x, axis):
    out = unsqueeze(x, axis)
    x._data, x._node, x._out_index = out._data, out._node, out._out_index
    return x


@defop(method=True)
def expand(x, shape):
    shape = _shape_arg(shape)
    shape = tuple(x.shape[i - (len(shape) - x.ndim)] if s in (-1,) else s
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@defop(method=True)
def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


@defop(method=True)
def broadcast_to(x, shape):
    return jnp.broadcast_to(x, _shape_arg(shape))


def broadcast_tensors(inputs, name=None):
    return run_op("broadcast_tensors",
                  lambda *xs: tuple(jnp.broadcast_arrays(*xs)), list(inputs))


@defop(method=True)
def tile(x, repeat_times):
    return jnp.tile(x, _shape_arg(repeat_times))


@defop(method=True)
def cast(x, dtype):
    return jnp.asarray(x).astype(dtypes.convert_dtype(dtype))


@defop(name="slice")
def slice(x, axes, starts, ends):
    idx = [jnp.s_[:]] * x.ndim
    for a, s, e in zip(_axes(axes), _axes(starts), _axes(ends)):
        idx[a] = jnp.s_[s:e]
    return x[tuple(idx)]


@defop()
def strided_slice(x, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for a, s, e, st in zip(_axes(axes), _axes(starts), _axes(ends), _axes(strides)):
        idx[a] = jnp.s_[s:e:st]
    return x[tuple(idx)]


@defop(method=True)
def gather(x, index, axis=0):
    index = jnp.asarray(index)
    if index.ndim == 0:
        index = index[None]
    return jnp.take(x, index, axis=int(axis) if not isinstance(axis, jnp.ndarray) else int(axis))


@defop()
def gather_nd(x, index):
    index = jnp.asarray(index)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@defop()
def scatter(x, index, updates, overwrite=True):
    index = jnp.asarray(index).reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@defop()
def scatter_nd(index, updates, shape):
    index = jnp.asarray(index)
    zeros = jnp.zeros(_shape_arg(shape), updates.dtype)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return zeros.at[idx].add(updates)


@defop()
def scatter_nd_add(x, index, updates):
    index = jnp.asarray(index)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@defop(method=True)
def index_select(x, index, axis=0):
    return jnp.take(x, jnp.asarray(index).reshape(-1), axis=int(axis))


@defop()
def index_add(x, index, axis, value):
    index = jnp.asarray(index).reshape(-1)
    x_m = jnp.moveaxis(x, int(axis), 0)
    v_m = jnp.moveaxis(jnp.asarray(value), int(axis), 0)
    out = x_m.at[index].add(v_m)
    return jnp.moveaxis(out, 0, int(axis))


@defop()
def index_put(x, indices, value, accumulate=False):
    idx = tuple(jnp.asarray(i) for i in indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


@defop(method=True)
def masked_select(x, mask):
    # dynamic output shape — materialized on host in eager mode
    return x[jnp.asarray(mask)]


@defop(method=True)
def masked_fill(x, mask, value):
    v = jnp.asarray(value, dtype=x.dtype) if not hasattr(value, "dtype") else value
    return jnp.where(jnp.asarray(mask), v, x)


@defop()
def masked_scatter(x, mask, value):
    mask = jnp.asarray(mask)
    mask_b = jnp.broadcast_to(mask, x.shape)
    flat_val = jnp.asarray(value).reshape(-1)
    pos = jnp.cumsum(mask_b.reshape(-1)) - 1
    take = flat_val[jnp.clip(pos, 0, flat_val.shape[0] - 1)]
    return jnp.where(mask_b, take.reshape(x.shape), x)


@defop(method=True)
def where(condition, x=None, y=None):
    return jnp.where(jnp.asarray(condition), x, y)


@defop()
def take_along_axis(arr, indices, axis, broadcast=True):
    indices = jnp.asarray(indices)
    return jnp.take_along_axis(arr, indices, axis=int(axis))


@defop()
def put_along_axis(arr, indices, values, axis, reduce="assign"):
    indices = jnp.asarray(indices)
    if reduce == "add":
        return jnp.put_along_axis(arr, indices, values, axis=int(axis), inplace=False, mode="add") \
            if hasattr(jnp, "put_along_axis") else _put_along(arr, indices, values, int(axis), "add")
    return _put_along(arr, indices, values, int(axis), "set")


def _put_along(arr, indices, values, axis, mode):
    arr_m = jnp.moveaxis(arr, axis, -1)
    idx_m = jnp.moveaxis(jnp.broadcast_to(indices, jnp.broadcast_shapes(
        indices.shape, arr.shape[:axis] + (indices.shape[axis],) + arr.shape[axis + 1:])), axis, -1)
    val_m = jnp.broadcast_to(jnp.asarray(values), idx_m.shape)
    grids = jnp.meshgrid(*[jnp.arange(s) for s in idx_m.shape[:-1]], indexing="ij") \
        if idx_m.ndim > 1 else []
    grids = [jnp.broadcast_to(g[..., None], idx_m.shape) for g in grids]
    index_tuple = tuple(grids) + (idx_m,)
    if mode == "add":
        out = arr_m.at[index_tuple].add(val_m)
    else:
        out = arr_m.at[index_tuple].set(val_m)
    return jnp.moveaxis(out, -1, axis)


@defop(method=True)
def flip(x, axis):
    ax = _axes(axis)
    return jnp.flip(x, axis=ax)


@defop()
def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@defop(method=True)
def roll(x, shifts, axis=None):
    sh = _axes(shifts) if not isinstance(shifts, int) else shifts
    ax = _axes(axis) if axis is not None else None
    return jnp.roll(x, sh, axis=ax)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # dynamic shape: eager-only (host round-trip), like the reference's
    # dynamic-output ops which are incompatible with static graphs too.
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    res = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    out = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    if axis is None:
        arr = arr.reshape(-1)
        axis = 0
    keep = np.ones(arr.shape[axis], dtype=bool)
    if arr.shape[axis] > 1:
        sl = [np.s_[:]] * arr.ndim
        sl[axis] = np.s_[1:]
        sl_prev = [np.s_[:]] * arr.ndim
        sl_prev[axis] = np.s_[:-1]
        diff = (arr[tuple(sl)] != arr[tuple(sl_prev)])
        other = tuple(i for i in range(arr.ndim) if i != axis)
        keep[1:] = diff.any(axis=other) if other else diff
    uniq = np.compress(keep, arr, axis=axis)
    outs = [Tensor(jnp.asarray(uniq))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, arr.shape[axis]))
        outs.append(Tensor(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


@defop(method=True)
def unbind(x, axis=0):
    axis = int(axis)
    return tuple(jnp.moveaxis(x, axis, 0))


def unstack(x, axis=0, num=None, name=None):
    return list(unbind(x, axis))


@defop()
def repeat_interleave(x, repeats, axis=None):
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    return jnp.repeat(x, r, axis=axis if axis is None else int(axis))


def as_strided(x, shape, stride, offset=0, name=None):
    arr = np.lib.stride_tricks.as_strided(
        np.asarray(x.numpy()).reshape(-1)[offset:],
        shape=tuple(shape),
        strides=tuple(s * x.numpy().dtype.itemsize for s in stride))
    return Tensor(jnp.asarray(arr.copy()))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


@defop()
def tensordot(x, y, axes=2):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(_axes(a)) if isinstance(a, (list, tuple)) else a for a in axes)
    return jnp.tensordot(x, y, axes=axes)


@defop()
def crop(x, shape=None, offsets=None):
    shape = _shape_arg(shape)
    offsets = _axes(offsets) if offsets is not None else (0,) * x.ndim
    if isinstance(offsets, int):
        offsets = (offsets,)
    idx = tuple(jnp.s_[o:o + s if s != -1 else None]
                for o, s in zip(offsets, shape))
    return x[idx]


@defop()
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", pad_from_left_axis=True):
    pad = _axes(pad) if not isinstance(pad, (list, tuple)) else tuple(
        int(p._data) if isinstance(p, Tensor) else int(p) for p in pad)
    nd = x.ndim
    if len(pad) == 2 * nd:
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        if pad_from_left_axis:
            widths = pairs
        else:
            # torch-style: first pair pads the last axis, walking backwards
            widths = [pairs[nd - 1 - i] for i in range(nd)]
    else:
        # paddle semantics (reference python/paddle/nn/functional/common.py
        # `pad`): the flat pad list pairs up as (left,right),(top,bottom),...
        # applied to the *innermost* spatial dim first. For channels-last
        # layouts (NHWC/NDHWC) the channel axis is skipped.
        k = len(pad) // 2
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(k)]
        widths = [(0, 0)] * nd
        if len(pad) in (2, 4, 6) and nd in (3, 4, 5) and data_format in (
                "NCL", "NCHW", "NCDHW", "NLC", "NHWC", "NDHWC"):
            if data_format.startswith("NC"):
                spatial = list(range(2, nd))
            else:
                spatial = list(range(1, nd - 1))
            if len(pairs) > len(spatial):
                raise ValueError(
                    f"pad list has {len(pairs)} (left,right) pairs but "
                    f"data_format {data_format} only has {len(spatial)} "
                    "spatial dims")
            # pairs[0] pads the innermost spatial dim (W), pairs[1] the next
            # (H), etc.
            for i, pair in enumerate(pairs):
                widths[spatial[len(spatial) - 1 - i]] = pair
        else:
            # generic: pad applies to the last k dims, innermost first
            for i, pair in enumerate(pairs):
                widths[nd - 1 - i] = pair
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    if jmode == "constant":
        return jnp.pad(x, widths, mode=jmode, constant_values=value)
    return jnp.pad(x, widths, mode=jmode)


@defop(differentiable=False)
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (input // shard_size) == shard_id
    return jnp.where(in_shard, input % shard_size, ignore_value)


def tolist(x):
    return x.numpy().tolist()


@defop()
def as_complex(x):
    return x[..., 0] + 1j * x[..., 1]


@defop()
def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@defop()
def atleast_1d(x):
    return jnp.atleast_1d(x)


@defop()
def atleast_2d(x):
    return jnp.atleast_2d(x)


@defop()
def atleast_3d(x):
    return jnp.atleast_3d(x)


@defop(method=True)
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@defop()
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    n = builtins_min(x.shape[axis1], x.shape[axis2])
    i = jnp.arange(n - builtins_abs(offset))
    r = i if offset >= 0 else i - offset
    c = i + offset if offset >= 0 else i
    x_m = jnp.moveaxis(jnp.moveaxis(x, axis1, 0), axis2 if axis2 > axis1 else axis2 + 1, 1)
    x_m = x_m.at[r, c].set(jnp.moveaxis(jnp.asarray(y), -1, 0))
    return jnp.moveaxis(jnp.moveaxis(x_m, 1, axis2 if axis2 > axis1 else axis2 + 1), 0, axis1)


builtins_min = min
builtins_abs = abs


@defop()
def select_scatter(x, values, axis, index):
    idx = [jnp.s_[:]] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(values)


@defop()
def slice_scatter(x, value, axes, starts, ends, strides):
    idx = [jnp.s_[:]] * x.ndim
    for a, s, e, st in zip(_axes(axes), _axes(starts), _axes(ends), _axes(strides)):
        idx[a] = jnp.s_[s:e:st]
    return x.at[tuple(idx)].set(value)


@defop()
def unflatten(x, axis, shape):
    axis = int(axis) % x.ndim
    new_shape = x.shape[:axis] + tuple(_shape_arg(shape)) + x.shape[axis + 1:]
    return jnp.reshape(x, new_shape)


@defop()
def unfold(x, axis, size, step):
    axis = int(axis) % x.ndim
    n = (x.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    windows = jax.vmap(
        lambda s: jax.lax.dynamic_slice_in_dim(x, s, size, axis))(starts)
    # windows: (n, ..., size at axis, ...) -> move window dim after axis
    return jnp.moveaxis(windows, 0, axis)


# ---------------------------------------------------------------------------
# __getitem__ / __setitem__ support (used by Tensor)
# ---------------------------------------------------------------------------
def _norm_index(idx):
    def conv(i):
        if isinstance(i, Tensor):
            return i._data
        if isinstance(i, (list, np.ndarray)):
            return jnp.asarray(i)
        return i
    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


def _getitem(x, idx):
    idx = _norm_index(idx)
    return run_op("getitem", lambda a: a[idx], [x])


def _setitem(x, idx, value):
    idx = _norm_index(idx)
    if isinstance(value, Tensor):
        out = run_op("setitem", lambda a, v: a.at[idx].set(v.astype(a.dtype)), [x, value])
    else:
        out = run_op("setitem", lambda a: a.at[idx].set(
            jnp.asarray(value, dtype=a.dtype)), [x])
    x._data, x._node, x._out_index = out._data, out._node, out._out_index
    if not out.stop_gradient:
        x.stop_gradient = False


@defop(method=True, inplace_method="index_fill_")
def index_fill(x, index, axis, value):
    """Fill rows of ``axis`` selected by ``index`` with ``value``
    (reference `tensor/manipulation.py:index_fill`)."""
    idx = jnp.asarray(index).reshape(-1)
    v = jnp.asarray(value, dtype=x.dtype)
    moved = jnp.moveaxis(x, axis, 0)
    moved = moved.at[idx].set(v)
    return jnp.moveaxis(moved, 0, axis)


# -- reference-op parity batch (phi/api/yaml: diag_embed, fill_diagonal,
#    fill_diagonal_tensor, multiplex, reverse, sequence_mask,
#    shuffle_channel, temporal_shift, gather_tree) ---------------------------
@defop(method=True)
def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    """Embed the last dim of ``x`` as the (offset) diagonal of new
    trailing matrices (reference op `diag_embed`,
    `phi/kernels/impl/diag_embed_impl.h`)."""
    x = jnp.asarray(x)
    n = x.shape[-1] + builtins.abs(int(offset))
    out_ndim = x.ndim + 1
    d1 = int(dim1) % out_ndim
    d2 = int(dim2) % out_ndim
    if d1 == d2:
        raise ValueError("diag_embed: dim1 and dim2 must differ")
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    r = idx + (-int(offset) if offset < 0 else 0)
    c = idx + (int(offset) if offset > 0 else 0)
    base = base.at[..., r, c].set(x)
    # base has the matrix at the trailing two dims; move them to (d1, d2)
    src = (out_ndim - 2, out_ndim - 1)
    if (d1, d2) != src:
        lo, hi = (d1, d2) if d1 < d2 else (d2, d1)
        base = jnp.moveaxis(base, src, (lo, hi))
        if d1 > d2:
            base = jnp.swapaxes(base, d1, d2)
    return base


@defop(method=True, inplace_method="fill_diagonal_")
def fill_diagonal(x, value, offset=0, wrap=False):
    """Fill the main (offset) diagonal of ``x`` (reference op
    `fill_diagonal`). With ``wrap`` the diagonal wraps for tall 2-D
    matrices, matching numpy/paddle semantics."""
    x = jnp.asarray(x)
    if x.ndim < 2:
        raise ValueError("fill_diagonal needs ndim >= 2")
    if x.ndim == 2:
        h, w = x.shape
        flat = jnp.arange(h * w)
        r, c = flat // w, flat % w
        if wrap:
            # numpy semantics: the diagonal stripe repeats every w+1
            # flat positions, continuing past the bottom of tall mats
            start = int(offset) if offset >= 0 else -int(offset) * w
            on = (flat >= start) & ((flat - start) % (w + 1) == 0)
        else:
            on = (c - r) == int(offset)
        return jnp.where(on.reshape(h, w), jnp.asarray(value, x.dtype), x)
    n = builtins.min(x.shape[-2:])
    idx = jnp.arange(n - builtins.abs(int(offset)))
    r = idx + (-int(offset) if offset < 0 else 0)
    c = idx + (int(offset) if offset > 0 else 0)
    return x.at[..., r, c].set(jnp.asarray(value, x.dtype))


@defop(method=True, inplace_method="fill_diagonal_tensor_")
def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1):
    """Write tensor ``y`` onto the (dim1, dim2) diagonal of ``x``
    (reference op `fill_diagonal_tensor`,
    `phi/kernels/gpu/fill_diagonal_tensor_kernel.cu`)."""
    x = jnp.asarray(x)
    d1 = int(dim1) % x.ndim
    d2 = int(dim2) % x.ndim
    # move the diagonal pair to the back, write, move back
    xt = jnp.moveaxis(x, (d1, d2), (-2, -1))
    n = builtins.min(xt.shape[-2:]) - builtins.abs(int(offset))
    idx = jnp.arange(n)
    r = idx + (-int(offset) if offset < 0 else 0)
    c = idx + (int(offset) if offset > 0 else 0)
    # y carries the batch dims (x minus dim1/dim2) plus the diagonal
    # length as its trailing dim — already aligned with xt[..., r, c]
    xt = xt.at[..., r, c].set(jnp.asarray(y, x.dtype))
    return jnp.moveaxis(xt, (-2, -1), (d1, d2))


@defop()
def multiplex(inputs, index):
    """Row-wise select across candidate tensors: out[i] =
    inputs[index[i]][i] (reference op `multiplex`,
    `phi/kernels/gpu/multiplex_kernel.cu`)."""
    stacked = jnp.stack([jnp.asarray(t) for t in inputs], axis=0)  # [K,N,...]
    idx = jnp.asarray(index).reshape(-1).astype(jnp.int32)
    n = stacked.shape[1]
    return stacked[idx, jnp.arange(n)]


def reverse(x, axis, name=None):
    """Deprecated paddle alias of :func:`flip` (reference legacy op
    `reverse`)."""
    return flip(x, axis)


@defop()
def sequence_mask(x, maxlen=None, dtype="int64"):
    """mask[i, j] = j < x[i] (reference op `sequence_mask`,
    `phi/kernels/funcs/sequence_mask_kernel.h`)."""
    lens = jnp.asarray(x)
    m = int(maxlen) if maxlen is not None else int(jnp.max(lens))
    mask = jnp.arange(m)[None, :] < lens.reshape(-1, 1)
    return mask.reshape(lens.shape + (m,)).astype(dtypes.convert_dtype(dtype))


@defop()
def shuffle_channel(x, group):
    """NCHW channel shuffle (reference op `shuffle_channel`) — the
    ShuffleNet channel mix: [N, G, C/G, H, W] transpose."""
    n, c, h, w = x.shape
    g = int(group)
    return x.reshape(n, g, c // g, h, w).swapaxes(1, 2).reshape(n, c, h, w)


@defop()
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    """TSM temporal shift (reference op `temporal_shift`,
    `phi/kernels/gpu/temporal_shift_kernel.cu`): within each segment
    group, shift the first fold of channels backward in time, the
    second forward, keep the rest."""
    if data_format == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    nt, c, h, w = x.shape
    t = int(seg_num)
    n = nt // t
    fold = int(c * float(shift_ratio))
    v = x.reshape(n, t, c, h, w)
    back = jnp.concatenate(
        [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(v[:, :1, fold:2 * fold]), v[:, :-1, fold:2 * fold]],
        axis=1)
    out = jnp.concatenate([back, fwd, v[:, :, 2 * fold:]], axis=2)
    out = out.reshape(nt, c, h, w)
    if data_format == "NHWC":
        out = jnp.moveaxis(out, 1, -1)
    return out


@defop(differentiable=False)
def gather_tree(ids, parents):
    """Beam-search back-trace (reference op `gather_tree`,
    `phi/kernels/gpu/gather_tree_kernel.cu`): ids/parents are
    [max_time, batch, beam]; walk parents from the last step back,
    emitting the full token path per beam."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    tmax, batch, beam = ids.shape
    b_idx = jnp.arange(batch)[:, None]
    k_idx = jnp.arange(beam)[None, :]

    def body(parent, t):                          # parent: [batch, beam]
        tok = ids[t][b_idx, parent]
        return parents[t][b_idx, parent], tok

    init = jnp.broadcast_to(k_idx, (batch, beam)).astype(parents.dtype)
    _, toks = jax.lax.scan(body, init, jnp.arange(tmax - 1, -1, -1))
    return toks[::-1]
