"""Elementwise + reduction math ops (reference: `python/paddle/tensor/math.py`).

Every op is a thin jax.numpy body registered through ``@defop`` — gradients
come from ``jax.vjp`` automatically (the reference hand-maintains these in
`backward.yaml` + CUDA grad kernels; here XLA differentiates and fuses them).
"""

from __future__ import annotations

from ..framework.dtype import default_int as _i64

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from .registry import defop

__all__ = [
    "trapezoid", "cumulative_trapezoid",
    "copysign", "nextafter", "gammaln", "gammainc", "gammaincc",
    "polygamma", "multigammaln", "sinc", "hypot", "i0e", "i1e",
    "p_norm", "frobenius_norm", "squared_l2_norm", "l1_norm",
    "clip_by_norm", "mean_all", "reduce_as", "elementwise_pow",
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "float_power", "maximum", "minimum", "fmax", "fmin",
    "exp", "expm1", "log", "log2", "log10", "log1p", "sqrt", "rsqrt",
    "square", "abs", "sign", "neg", "reciprocal", "floor", "ceil", "round",
    "trunc", "frac", "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
    "sinh", "cosh", "asinh", "acosh", "atanh", "erf", "erfinv",
    "sigmoid", "logit", "logaddexp",
    "sum", "mean", "max", "min", "prod", "amax", "amin",
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp", "logsumexp",
    "clip", "lerp", "nan_to_num", "isfinite", "isinf", "isnan",
    "all", "any", "count_nonzero", "nansum", "nanmean",
    "multiply_", "add_n", "addmm", "inner", "outer", "trace",
    "diff", "angle", "conj", "real", "imag", "gcd", "lcm",
    "heaviside", "rad2deg", "deg2rad", "take", "broadcast_shape",
    "increment", "kron", "ldexp", "digamma", "lgamma", "i0", "i1",
    "tanh", "stanh", "softplus_math", "renorm", "vander",
]

_default_axis_none = object()


def _ax(axis):
    if axis is None or axis is _default_axis_none:
        return None
    if isinstance(axis, Tensor):
        a = axis.numpy()
        return tuple(int(v) for v in a.reshape(-1)) if a.size > 1 else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


# -- binary elementwise -----------------------------------------------------
@defop(method=True)
def add(x, y):
    return jnp.add(x, y)


@defop(method=True)
def subtract(x, y):
    return jnp.subtract(x, y)


@defop(method=True)
def multiply(x, y):
    return jnp.multiply(x, y)


@defop(method=True)
def divide(x, y):
    return jnp.divide(x, y)


@defop(method=True)
def floor_divide(x, y):
    return jnp.floor_divide(x, y)


@defop(method=True)
def mod(x, y):
    return jnp.mod(x, y)


remainder = mod


@defop(name="pow", method=True)
def pow(x, y):
    return jnp.power(x, y)


@defop()
def float_power(x, y):
    return jnp.float_power(x, y)


@defop(method=True)
def maximum(x, y):
    return jnp.maximum(x, y)


@defop(method=True)
def minimum(x, y):
    return jnp.minimum(x, y)


@defop(method=True)
def fmax(x, y):
    return jnp.fmax(x, y)


@defop(method=True)
def fmin(x, y):
    return jnp.fmin(x, y)


@defop()
def atan2(x, y):
    return jnp.arctan2(x, y)


@defop()
def logaddexp(x, y):
    return jnp.logaddexp(x, y)


@defop()
def heaviside(x, y):
    return jnp.heaviside(x, y)


@defop(differentiable=False)
def gcd(x, y):
    return jnp.gcd(x, y)


@defop(differentiable=False)
def lcm(x, y):
    return jnp.lcm(x, y)


@defop()
def ldexp(x, y):
    return jnp.ldexp(x, y)


@defop()
def kron(x, y):
    return jnp.kron(x, y)


# -- unary elementwise ------------------------------------------------------
def _unary(name, fn, **kw):
    @defop(name=name, method=True, inplace_method=name + "_", **kw)
    def op(x):
        return fn(x)
    op.__name__ = name
    return op


exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
abs = _unary("abs", jnp.abs)
sign = _unary("sign", jnp.sign)
neg = _unary("neg", jnp.negative)
reciprocal = _unary("reciprocal", jnp.reciprocal)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
tanh = _unary("tanh", jnp.tanh)
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
i0 = _unary("i0", jax.scipy.special.i0)
i1 = _unary("i1", jax.scipy.special.i1)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)


@defop()
def frac(x):
    return x - jnp.trunc(x)


@defop()
def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@defop()
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


@defop(name="softplus_math")
def softplus_math(x, beta=1.0, threshold=20.0):
    return jnp.where(x * beta > threshold, x, jnp.log1p(jnp.exp(beta * x)) / beta)


# -- reductions -------------------------------------------------------------
@defop(name="sum", method=True)
def sum(x, axis=None, dtype=None, keepdim=False):
    return jnp.sum(x, axis=_ax(axis), dtype=dtype, keepdims=keepdim)


@defop(method=True)
def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=_ax(axis), keepdims=keepdim)


@defop(name="max", method=True)
def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=_ax(axis), keepdims=keepdim)


@defop(name="min", method=True)
def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=_ax(axis), keepdims=keepdim)


@defop(method=True)
def prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=_ax(axis), dtype=dtype, keepdims=keepdim)


amax = max
amin = min


@defop(name="all", method=True, differentiable=False)
def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=_ax(axis), keepdims=keepdim)


@defop(name="any", method=True, differentiable=False)
def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=_ax(axis), keepdims=keepdim)


@defop(differentiable=False)
def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=_ax(axis), keepdims=keepdim)


@defop()
def nansum(x, axis=None, dtype=None, keepdim=False):
    return jnp.nansum(x, axis=_ax(axis), dtype=dtype, keepdims=keepdim)


@defop()
def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=_ax(axis), keepdims=keepdim)


@defop(method=True)
def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=_ax(axis), keepdims=keepdim)


# -- scans ------------------------------------------------------------------
@defop(method=True)
def cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=int(axis), dtype=dtype)


@defop(method=True)
def cumprod(x, dim=None, dtype=None):
    if dim is None:
        x = x.reshape(-1)
        dim = 0
    return jnp.cumprod(x, axis=int(dim), dtype=dtype)


@defop()
def cummax(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.associative_scan(jnp.maximum, x, axis=int(axis))
    return vals, _cum_argext(x, int(axis), True)


@defop()
def cummin(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.associative_scan(jnp.minimum, x, axis=int(axis))
    return vals, _cum_argext(x, int(axis), False)


def _cum_argext(x, axis, is_max):
    n = x.shape[axis]
    pos = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1 for i in range(x.ndim)])
    pos = jnp.broadcast_to(pos, x.shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        keep_a = av >= bv if is_max else av <= bv
        return jnp.where(keep_a, av, bv), jnp.where(keep_a, ai, bi)

    _, idx = jax.lax.associative_scan(combine, (x, pos), axis=axis)
    return idx.astype(_i64())


@defop()
def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=int(axis))


# -- misc -------------------------------------------------------------------
@defop(method=True, inplace_method="clip_")
def clip(x, min=None, max=None):
    mn = min._data if isinstance(min, Tensor) else min
    mx = max._data if isinstance(max, Tensor) else max
    return jnp.clip(x, mn, mx)


@defop()
def lerp(x, y, weight):
    return x + weight * (y - x)


@defop()
def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@defop(method=True, differentiable=False)
def isfinite(x):
    return jnp.isfinite(x)


@defop(method=True, differentiable=False)
def isinf(x):
    return jnp.isinf(x)


@defop(method=True, differentiable=False)
def isnan(x):
    return jnp.isnan(x)


def add_n(inputs, name=None):
    from ..framework.tensor import run_op
    if isinstance(inputs, Tensor):
        return inputs
    return run_op("add_n", lambda *xs: jnp.sum(jnp.stack(
        [jnp.asarray(x) for x in xs]), axis=0), list(inputs))


@defop()
def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


@defop()
def inner(x, y):
    return jnp.inner(x, y)


@defop()
def outer(x, y):
    return jnp.outer(jnp.ravel(x), jnp.ravel(y))


@defop(method=True)
def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@defop()
def diff(x, n=1, axis=-1, prepend=None, append=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@defop(differentiable=False)
def increment(x, value=1.0):
    return x + value


@defop(method=True)
def take(x, index, mode="raise"):
    return jnp.take(jnp.ravel(x), index, mode="clip" if mode != "raise" else "clip")


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@defop()
def renorm(x, p, axis, max_norm):
    norms = jnp.sum(jnp.abs(x) ** p,
                    axis=tuple(i for i in range(x.ndim) if i != axis),
                    keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


@defop()
def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


def multiply_(x, y):
    out = multiply(x, y)
    x._data, x._node, x._out_index = out._data, out._node, out._out_index
    return x


@defop(method=True)
def trapezoid(y, x=None, dx=None, axis=-1):
    """Trapezoidal rule integral (reference `tensor/math.py:trapezoid`)."""
    if x is not None and dx is not None:
        raise ValueError("pass either x or dx, not both")
    if x is not None:
        return jnp.trapezoid(y, x=x, axis=axis)
    return jnp.trapezoid(y, dx=1.0 if dx is None else dx, axis=axis)


@defop(method=True)
def cumulative_trapezoid(y, x=None, dx=None, axis=-1):
    """Cumulative trapezoid (reference `tensor/math.py`): running sum of
    the per-segment trapezoid areas along ``axis``."""
    if x is not None and dx is not None:
        raise ValueError("pass either x or dx, not both")
    y0 = jax.lax.slice_in_dim(y, 0, y.shape[axis] - 1, axis=axis)
    y1 = jax.lax.slice_in_dim(y, 1, y.shape[axis], axis=axis)
    if x is not None:
        x = jnp.asarray(x)
        if x.ndim == 1:
            shape = [1] * y.ndim
            shape[axis] = x.shape[0]
            x = x.reshape(shape)
        d = jnp.diff(x, axis=axis)
    else:
        d = 1.0 if dx is None else dx
    return jnp.cumsum((y0 + y1) * 0.5 * d, axis=axis)


# -- special functions (reference `phi/api/yaml/ops.yaml`: copysign,
#    nextafter, gammaln, gammainc(c), polygamma, i0e, i1e) ------------------
@defop(method=True, inplace_method="copysign_")
def copysign(x, y):
    """Magnitude of ``x`` with the sign of ``y`` (reference op
    `copysign`, CUDA kernel `phi/kernels/gpu/copysign_kernel.cu`)."""
    return jnp.copysign(x, y)


@defop(method=True)
def nextafter(x, y):
    """Next representable float after ``x`` toward ``y`` (reference op
    `nextafter`)."""
    return jnp.nextafter(x, y)


gammaln = _unary("gammaln", jax.scipy.special.gammaln)
i0e = _unary("i0e", jax.scipy.special.i0e)
i1e = _unary("i1e", jax.scipy.special.i1e)


@defop(method=True, inplace_method="gammainc_")
def gammainc(x, y):
    """Regularized lower incomplete gamma P(x, y) (reference op
    `gammainc`)."""
    return jax.scipy.special.gammainc(x, y)


@defop(method=True, inplace_method="gammaincc_")
def gammaincc(x, y):
    """Regularized upper incomplete gamma Q(x, y) (reference op
    `gammaincc`, `phi/kernels/impl/gammaincc_kernel_impl.h`)."""
    return jax.scipy.special.gammaincc(x, y)


@defop(method=True, inplace_method="polygamma_")
def polygamma(x, n):
    """n-th derivative of digamma at ``x`` (reference op `polygamma`)."""
    return jax.scipy.special.polygamma(n, x)


@defop(method=True)
def multigammaln(x, p):
    """Log multivariate gamma (reference `tensor/math.py:multigammaln`)."""
    return jax.scipy.special.multigammaln(x, p)


@defop(method=True)
def sinc(x):
    """sin(pi x)/(pi x) (reference op `sinc`)."""
    return jnp.sinc(x)


@defop(method=True)
def hypot(x, y):
    """sqrt(x^2 + y^2) without overflow (reference `tensor/math.py`)."""
    return jnp.hypot(x, y)


# -- reduction / norm kernels (reference ops p_norm, frobenius_norm,
#    squared_l2_norm, l1_norm, clip_by_norm, mean_all, reduce_as) -----------
@defop()
def p_norm(x, porder=2.0, axis=None, keepdim=False, asvector=False):
    """Vector p-norm along ``axis`` (reference op `p_norm`,
    `phi/kernels/gpu/p_norm_kernel.cu`). ``asvector`` flattens first."""
    if asvector or axis is None:
        x = x.reshape(-1)
        axis = 0
    p = float(porder)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)


@defop()
def frobenius_norm(x, axis=None, keepdim=False):
    """Frobenius norm over the trailing two dims by default (reference op
    `frobenius_norm`)."""
    if axis is None:
        axis = (-2, -1) if x.ndim >= 2 else (-1,)
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))


@defop()
def squared_l2_norm(x):
    """sum(x^2) as a 0-d tensor (reference op `squared_l2_norm` — the
    gradient-clipping workhorse)."""
    return jnp.sum(jnp.square(x))


@defop()
def l1_norm(x):
    """sum(|x|) (reference op `l1_norm`)."""
    return jnp.sum(jnp.abs(x))


@defop()
def clip_by_norm(x, max_norm):
    """Scale ``x`` so its L2 norm is at most ``max_norm`` (reference op
    `clip_by_norm`, `phi/kernels/clip_by_norm_kernel.h`)."""
    nrm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.minimum(max_norm / jnp.maximum(nrm, 1e-12), 1.0)
    return x * scale


@defop()
def mean_all(x):
    """Global mean as a 0-d tensor (reference op `mean_all`)."""
    return jnp.mean(x)


@defop()
def reduce_as(x, target):
    """Sum-reduce ``x`` down to ``target``'s shape (reference op
    `reduce_as` — the broadcast-gradient reducer)."""
    t_shape = target.shape if hasattr(target, "shape") else tuple(target)
    extra = x.ndim - len(t_shape)
    if extra:
        x = jnp.sum(x, axis=tuple(range(extra)))
    axes = tuple(i for i, (a, b) in enumerate(zip(x.shape, t_shape))
                 if a != b and b == 1)
    if axes:
        x = jnp.sum(x, axis=axes, keepdims=True)
    return x


@defop(name="elementwise_pow", method=False)
def elementwise_pow(x, y):
    """Elementwise x**y (reference legacy op `elementwise_pow`)."""
    return jnp.power(x, y)
