"""Tensor attribute queries (reference: `python/paddle/tensor/attribute.py`)."""

from __future__ import annotations

from ..framework.dtype import default_int as _i64

import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["shape", "rank", "is_floating_point", "is_integer", "is_complex",
           "numel"]


def shape(x):
    return Tensor(jnp.asarray(x.shape, dtype=jnp.int32))


def rank(x):
    return Tensor(jnp.asarray(x.ndim, dtype=jnp.int32))


def is_floating_point(x):
    return jnp.issubdtype(x.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(x.dtype, jnp.integer)


def is_complex(x):
    return jnp.issubdtype(x.dtype, jnp.complexfloating)


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=_i64()))
