"""Search / sort ops (reference: `python/paddle/tensor/search.py`).

Ops with integer index outputs (argmax/argsort/topk) compute indices under
stop-grad and recover differentiable values via gather — so values carry
gradients while indices stay integer, matching the reference's grad behavior.
"""

from __future__ import annotations

from ..framework.dtype import default_int as _i64, convert_dtype as _cvt

import numpy as np
import jax.numpy as jnp

from ..framework.tensor import Tensor, run_op
from .registry import defop
from . import manipulation

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
    "where_index", "nonzero", "index_sample", "searchsorted", "bucketize",
    "masked_select_idx", "top_p_sampling",
]


@defop(method=True, differentiable=False)
def argmax(x, axis=None, keepdim=False, dtype="int64"):
    if axis is None:
        out = jnp.argmax(x.reshape(-1))
        return out.astype(_cvt(dtype))
    out = jnp.argmax(x, axis=int(axis), keepdims=keepdim)
    return out.astype(_cvt(dtype))


@defop(method=True, differentiable=False)
def argmin(x, axis=None, keepdim=False, dtype="int64"):
    if axis is None:
        out = jnp.argmin(x.reshape(-1))
        return out.astype(_cvt(dtype))
    out = jnp.argmin(x, axis=int(axis), keepdims=keepdim)
    return out.astype(_cvt(dtype))


@defop(method=True, differentiable=False)
def argsort(x, axis=-1, descending=False, stable=False):
    idx = jnp.argsort(x, axis=int(axis), stable=stable,
                      descending=descending)
    return idx.astype(_i64())


def sort(x, axis=-1, descending=False, stable=False, name=None):
    idx = argsort(x, axis=axis, descending=descending, stable=stable)
    return manipulation.take_along_axis(x, idx, axis=axis)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    ax = -1 if axis is None else int(axis)

    def idx_fn(a):
        a_m = a if largest else -a
        if ax != -1 and ax != a.ndim - 1:
            a_m = jnp.moveaxis(a_m, ax, -1)
        import jax
        _, idx = jax.lax.top_k(a_m, k)
        if ax != -1 and ax != a.ndim - 1:
            idx = jnp.moveaxis(idx, -1, ax)
        return idx.astype(_i64())

    indices = run_op("topk_indices", idx_fn, [x], differentiable=False)
    values = manipulation.take_along_axis(x, indices, axis=ax)
    return values, indices


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    ax = int(axis)
    idx_sorted = argsort(x, axis=ax)
    sel = manipulation.take_along_axis(
        idx_sorted, Tensor(jnp.full(
            tuple(1 if i == ax % x.ndim else s for i, s in enumerate(x.shape)),
            k - 1, dtype=_i64())), axis=ax)
    vals = manipulation.take_along_axis(x, sel, axis=ax)
    if not keepdim:
        vals = manipulation.squeeze(vals, axis=ax)
        sel = manipulation.squeeze(sel, axis=ax)
    return vals, sel


def mode(x, axis=-1, keepdim=False, name=None):
    # host computation (dynamic counting), eager-only like reference dynamic ops
    arr = np.asarray(x.numpy())
    ax = int(axis) % arr.ndim
    moved = np.moveaxis(arr, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=arr.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for i, row in enumerate(flat):
        uniq, counts = np.unique(row, return_counts=True)
        best = uniq[np.argmax(counts)]
        vals[i] = best
        idxs[i] = np.where(row == best)[0][-1]
    shape = moved.shape[:-1]
    v = vals.reshape(shape)
    ind = idxs.reshape(shape)
    if keepdim:
        v = np.expand_dims(v, ax)
        ind = np.expand_dims(ind, ax)
    return Tensor(jnp.asarray(v)), Tensor(jnp.asarray(ind))


def nonzero(x, as_tuple=False):
    # dynamic output shape → host round-trip in eager mode
    arr = np.asarray(x.numpy())
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(a.astype(np.int64))[:, None]) for a in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


where_index = nonzero


@defop()
def index_sample(x, index):
    return jnp.take_along_axis(x, jnp.asarray(index), axis=1)


@defop(differentiable=False)
def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        import jax
        flat_seq = sorted_sequence.reshape(-1, sorted_sequence.shape[-1])
        flat_val = jnp.asarray(values).reshape(-1, jnp.asarray(values).shape[-1])
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(flat_seq, flat_val)
        out = out.reshape(jnp.asarray(values).shape)
    return out.astype(jnp.int32 if out_int32 else _i64())


@defop(differentiable=False)
def bucketize(x, sorted_sequence, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32 if out_int32 else _i64())


def masked_select_idx(x, mask):
    return manipulation.masked_select(x, mask)


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Sample one id per row from the top-p nucleus (reference
    `python/paddle/tensor/search.py:1261`, CUDA kernel
    `phi/kernels/gpu/top_p_sampling_kernel.cu`). ``x`` [B, V] holds
    probabilities, ``ps`` [B] the cumulative threshold, ``threshold`` an
    optional absolute probability floor. Returns (values [B, 1],
    ids [B, 1] int64).

    TPU-native: sort + masked Gumbel-argmax — static shapes, no
    rejection loop.
    """
    import jax

    from ..framework import random as frandom
    from ..framework.tensor import run_op

    key = jax.random.key(seed) if seed is not None else frandom.next_key()

    def fn(x, ps, thr, key):
        sx_idx = jnp.argsort(-x, axis=-1)
        sx = jnp.take_along_axis(x, sx_idx, axis=-1)
        cum_before = jnp.cumsum(sx, axis=-1) - sx
        keep = cum_before < ps[:, None]          # always keeps the top-1
        if thr is not None:
            keep &= (sx >= thr[:, None]) | (cum_before <= 0)
        logits = jnp.where(keep, jnp.log(jnp.maximum(sx, 1e-38)), -1e30)
        j = jax.random.categorical(key, logits, axis=-1)      # [B]
        val = jnp.take_along_axis(sx, j[:, None], axis=-1)
        ids = jnp.take_along_axis(sx_idx, j[:, None], axis=-1)
        return val, ids.astype(_i64())

    return run_op("top_p_sampling", fn, (x, ps, threshold, key),
                  differentiable=False)
