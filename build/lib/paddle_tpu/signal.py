"""``paddle.signal`` — STFT / ISTFT (reference: `python/paddle/signal.py`
stft:246, istft:423; CUDA frame/overlap-add kernels in
`phi/kernels/gpu/{frame,overlap_add}_*`).

TPU-native: framing is a strided gather XLA folds into the FFT's input
layout; the FFT itself is XLA's native (MXU-accelerated for the matmul
decomposition sizes). ISTFT overlap-add is a scatter-add over frame
positions plus the standard squared-window normalization.
"""

from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import run_op

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice ``x`` into overlapping frames along ``axis`` (reference
    `signal.py:frame`). For axis=-1, [..., N] -> [..., frame_length,
    num_frames]; for axis=0, [N, ...] -> [num_frames, frame_length, ...]."""
    if axis not in (-1, 0):
        raise ValueError("frame: axis must be 0 or -1")

    def fn(x):
        xx = jnp.moveaxis(x, 0, -1) if axis == 0 else x
        n = xx.shape[-1]
        num = 1 + (n - frame_length) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(frame_length)[None, :]
        out = xx[..., idx]                       # [..., num, frame_length]
        out = jnp.swapaxes(out, -1, -2)          # [..., frame_length, num]
        if axis == 0:
            # [..., frame_length, num] -> [num, frame_length, ...]
            out = jnp.moveaxis(out, (-1, -2), (0, 1))
        return out

    return run_op("frame", fn, (x,))


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of :func:`frame` (reference `signal.py:overlap_add`):
    axis=-1 takes [..., frame_length, num_frames] -> [..., N]; axis=0
    takes [num_frames, frame_length, ...] -> [N, ...]."""
    if axis not in (-1, 0):
        raise ValueError("overlap_add: axis must be 0 or -1")

    def fn(x):
        # axis=0 input layout is [num, frame_length, ...]; bring it to the
        # canonical [..., frame_length, num] before the scatter-add.
        xx = jnp.moveaxis(x, (0, 1), (-1, -2)) if axis == 0 else x
        fl, num = xx.shape[-2], xx.shape[-1]
        n = (num - 1) * hop_length + fl
        starts = jnp.arange(num) * hop_length
        idx = (starts[None, :] + jnp.arange(fl)[:, None])  # [fl, num]
        out = jnp.zeros(xx.shape[:-2] + (n,), xx.dtype)
        out = out.at[..., idx].add(xx)
        return jnp.moveaxis(out, -1, 0) if axis == 0 else out

    return run_op("overlap_add", fn, (x,))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (reference `signal.py:246`).

    x: [B, N] or [N] real (complex allowed with onesided=False). Returns
    complex [B, n_fft//2 + 1, num_frames] (onesided) or
    [B, n_fft, num_frames].
    """
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(x, window):
        squeeze = x.ndim == 1
        xx = x[None] if squeeze else x
        is_complex = jnp.iscomplexobj(xx)
        if is_complex and onesided:
            raise ValueError("onesided=True requires real input")
        if window is None:
            win = jnp.ones((win_length,), jnp.float32)
        else:
            win = window.reshape(-1)
        if win_length < n_fft:  # center-pad the window to n_fft
            pad = n_fft - win_length
            win = jnp.pad(win, (pad // 2, pad - pad // 2))
        if center:
            xx = jnp.pad(xx, [(0, 0)] * (xx.ndim - 1)
                         + [(n_fft // 2, n_fft // 2)], mode=pad_mode)
        n = xx.shape[-1]
        num = 1 + (n - n_fft) // hop_length
        starts = jnp.arange(num) * hop_length
        idx = starts[:, None] + jnp.arange(n_fft)[None, :]
        frames = xx[..., idx] * win[None, None, :]   # [B, num, n_fft]
        if onesided:
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        spec = jnp.swapaxes(spec, -1, -2)            # [B, freq, num]
        return spec[0] if squeeze else spec

    return run_op("stft", fn, (x, window))


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, return_complex=False,
          length=None, name=None):
    """Inverse STFT (reference `signal.py:423`): least-squares overlap-add
    with squared-window normalization."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(x, window):
        squeeze = x.ndim == 2
        spec = x[None] if squeeze else x             # [B, freq, num]
        if window is None:
            win = jnp.ones((win_length,), jnp.float32)
        else:
            win = window.reshape(-1)
        if win_length < n_fft:
            pad = n_fft - win_length
            win = jnp.pad(win, (pad // 2, pad - pad // 2))
        frames = jnp.swapaxes(spec, -1, -2)          # [B, num, freq]
        if normalized:
            frames = frames * jnp.sqrt(
                jnp.asarray(n_fft, jnp.float32))
        if onesided:
            sig = jnp.fft.irfft(frames, n=n_fft, axis=-1)
        else:
            sig = jnp.fft.ifft(frames, axis=-1)
            if not return_complex:
                sig = sig.real
        sig = sig * win[None, None, :]
        num = sig.shape[1]
        n = (num - 1) * hop_length + n_fft
        starts = jnp.arange(num) * hop_length
        idx = (starts[:, None] + jnp.arange(n_fft)[None, :])
        out = jnp.zeros(sig.shape[:1] + (n,), sig.dtype)
        out = out.at[:, idx].add(sig)
        norm = jnp.zeros((n,), jnp.float32).at[idx.reshape(-1)].add(
            jnp.tile(win.astype(jnp.float32) ** 2, (num,)))
        out = out / jnp.where(norm > 1e-11, norm, 1.0)
        if center:
            out = out[:, n_fft // 2:]
            if length is not None:
                out = out[:, :length]
            else:
                out = out[:, :n - n_fft]
        elif length is not None:
            out = out[:, :length]
        return out[0] if squeeze else out

    return run_op("istft", fn, (x, window))
