"""nn.utils — parameter vectorization + clip utilities.

Reference: `python/paddle/nn/utils/`.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...framework.tensor import Tensor
from ..clip import clip_grad_norm_, clip_grad_value_  # noqa: F401

__all__ = ["parameters_to_vector", "vector_to_parameters",
           "clip_grad_norm_", "clip_grad_value_", "weight_norm",
           "remove_weight_norm", "spectral_norm"]


def parameters_to_vector(parameters):
    return Tensor(jnp.concatenate(
        [p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters):
    offset = 0
    for p in parameters:
        n = 1
        for s in p._data.shape:
            n *= s
        p._data = vec._data[offset:offset + n].reshape(p._data.shape) \
            .astype(p._data.dtype)
        offset += n


def _norm_except(v, dim, eps=1e-12):
    """L2 norm of ``v`` over every axis except ``dim`` (keepdims), the
    reference's norm_except_dim (`nn/utils/weight_norm_hook.py`)."""
    from ...tensor import math as tmath
    if dim is None:
        axes = None
    else:
        axes = [i for i in range(v.ndim) if i != dim]
    sq = (v * v).sum(axis=axes, keepdim=True)
    return (sq + eps).sqrt()


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as g * v / ||v|| (reference:
    `python/paddle/nn/utils/weight_norm_hook.py` ``weight_norm``).
    ``g`` and ``v`` become the trainable parameters; the effective weight
    is recomputed (on the tape) before every forward."""
    from ...framework.tensor import Parameter

    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"layer has no parameter {name!r}")
    g0 = _norm_except(w, dim)
    v = Parameter(w._data)
    g = Parameter(g0._data)
    del layer._parameters[name]
    setattr(layer, name + "_v", v)
    setattr(layer, name + "_g", g)

    def compute(lyr):
        vv = getattr(lyr, name + "_v")
        gg = getattr(lyr, name + "_g")
        wv = vv * (gg / _norm_except(vv, dim))
        object.__setattr__(lyr, name, wv)

    def hook(lyr, inputs):
        compute(lyr)
        return None

    handle = layer.register_forward_pre_hook(hook)
    if not hasattr(layer, "_weight_norm_hooks"):
        layer._weight_norm_hooks = {}
    layer._weight_norm_hooks[name] = (handle, dim)
    compute(layer)   # weight exists even before the first forward
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain parameter and drop the hook."""
    from ...framework.tensor import Parameter

    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"{name!r} is not weight-normalized")
    handle, dim = hooks.pop(name)
    handle.remove()
    v = getattr(layer, name + "_v")
    g = getattr(layer, name + "_g")
    w = (v * (g / _norm_except(v, dim))).detach()
    del layer._parameters[name + "_v"]
    del layer._parameters[name + "_g"]
    layer.__dict__.pop(name + "_v", None)
    layer.__dict__.pop(name + "_g", None)
    layer.__dict__.pop(name, None)
    setattr(layer, name, Parameter(w._data))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Spectral normalization hook (reference:
    `python/paddle/nn/utils/spectral_norm_hook.py`): divides the weight by
    its largest singular value, estimated by power iteration on a
    persistent ``u`` vector."""
    import numpy as np
    from ...framework.tensor import Parameter, Tensor

    w = getattr(layer, name)
    if dim is None:
        dim = 1 if type(layer).__name__.endswith("Transpose") else 0
    mat = jnp.moveaxis(w._data, dim, 0)
    rows = mat.shape[0]
    orig = Parameter(w._data)
    del layer._parameters[name]
    setattr(layer, name + "_orig", orig)
    u0 = np.random.RandomState(0).randn(rows).astype("float32")
    layer.register_buffer(name + "_u",
                          Tensor(jnp.asarray(u0 / np.linalg.norm(u0))))

    def compute(lyr):
        wo = getattr(lyr, name + "_orig")
        u = getattr(lyr, name + "_u")
        w2 = jnp.moveaxis(wo._data, dim, 0).reshape(rows, -1)
        uu = u._data
        for _ in range(n_power_iterations):
            vv = w2.T @ uu
            vv = vv / (jnp.linalg.norm(vv) + eps)
            uu = w2 @ vv
            uu = uu / (jnp.linalg.norm(uu) + eps)
        u._data = uu                      # persistent power-iteration state
        # u/v are constants but sigma = u^T W v stays ON the tape, so
        # backward carries the -W·(u v^T)/sigma^2 term (reference
        # spectral_norm_hook keeps sigma in the graph)
        perm = [dim] + [i for i in range(wo.ndim) if i != dim]
        from ...tensor import manipulation as M
        w2_t = M.transpose(wo, perm).reshape([rows, -1])
        uv = Tensor(uu[:, None] * vv[None, :])
        sigma = (w2_t * uv).sum()
        object.__setattr__(lyr, name, wo / sigma)

    layer.register_forward_pre_hook(lambda lyr, inputs: compute(lyr))
    compute(layer)
    return layer
