"""Convolution functionals over ``lax.conv_general_dilated``.

Reference: `python/paddle/nn/functional/conv.py` (conv1d/2d/3d and
transpose variants). TPU-first: one XLA convolution per call — the MXU path —
with NCHW/NHWC handled by dimension numbers, groups by feature_group_count.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...tensor.registry import defop

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(e) for e in v)
    return (int(v),) * n


def _dim_numbers(ndim, channel_last):
    if ndim == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if ndim == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _norm_padding(padding, nd):
    """Paddle padding forms: int, 'SAME'/'VALID', [p]*nd, or explicit pairs."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd and all(isinstance(p, int) for p in padding):
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    # list of pairs (possibly including batch/channel dims — strip those)
    pairs = [tuple(int(e) for e in p) for p in padding]
    if len(pairs) == nd + 2:
        pairs = pairs[2:]
    return pairs


def _weight_to_io(w, nd, channel_last):
    """Paddle weights are [out_c, in_c/groups, *k]; lax channel-last specs
    want [*k, in_c/groups, out_c]."""
    if not channel_last:
        return w
    perm = tuple(range(2, 2 + nd)) + (1, 0)
    return jnp.transpose(w, perm)


def _conv(x, weight, bias, stride, padding, dilation, groups, nd,
          data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    dn = _dim_numbers(nd, channel_last)
    out = jax.lax.conv_general_dilated(
        x, _weight_to_io(weight, nd, channel_last),
        window_strides=_tuple(stride, nd),
        padding=_norm_padding(padding, nd),
        rhs_dilation=_tuple(dilation, nd),
        dimension_numbers=dn,
        feature_group_count=int(groups),
        preferred_element_type=None)
    if bias is not None:
        if channel_last:
            out = out + bias
        else:
            out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@defop()
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL"):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, fmt)


@defop()
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format)


@defop()
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, nd, data_format):
    channel_last = data_format in ("NHWC", "NWC", "NDHWC", "NLC")
    strides = _tuple(stride, nd)
    dilations = _tuple(dilation, nd)
    pad = _norm_padding(padding, nd)
    opad = _tuple(output_padding, nd) if output_padding is not None else (0,) * nd
    # paddle transpose-conv weight is [in_c, out_c/groups, *k]
    k = weight.shape[2:]
    if isinstance(pad, str):
        if pad == "VALID":
            pad = [(0, 0)] * nd
        else:  # SAME
            pad = [((dilations[i] * (k[i] - 1)) // 2,) * 2 for i in range(nd)]
    # conv_transpose as input-dilated conv: lhs_dilation=strides,
    # padding adjusted: p' = d*(k-1) - p
    eff = [dilations[i] * (k[i] - 1) for i in range(nd)]
    tpad = [(eff[i] - pad[i][0], eff[i] - pad[i][1] + opad[i])
            for i in range(nd)]
    dn = _dim_numbers(nd, channel_last)
    g = int(groups)
    # weight [in_c, out_c/g, *k] -> flip spatial, swap to [out_c, in_c/g, *k]
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if g == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        in_c = w.shape[0]
        w = w.reshape((g, in_c // g) + w.shape[1:])
        w = jnp.swapaxes(w, 1, 2)  # [g, out/g, in/g, *k]
        w = w.reshape((-1, in_c // g) + w.shape[3:])
    out = jax.lax.conv_general_dilated(
        x, _weight_to_io(w, nd, channel_last),
        window_strides=(1,) * nd,
        padding=tpad,
        lhs_dilation=strides,
        rhs_dilation=dilations,
        dimension_numbers=dn,
        feature_group_count=g)
    if bias is not None:
        if channel_last:
            out = out + bias
        else:
            out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@defop()
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCL"):
    fmt = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, fmt)


@defop()
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCHW", output_size=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format)


@defop()
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, dilation=1, groups=1,
                     data_format="NCDHW", output_size=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format)
