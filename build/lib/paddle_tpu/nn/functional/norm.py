"""Normalization functionals.

Reference: `python/paddle/nn/functional/norm.py` (layer_norm, batch_norm,
instance_norm, group_norm, local_response_norm) plus the fused
``rms_norm`` from `python/paddle/incubate/nn/functional/fused_rms_norm.py`.
All are single fused jnp expressions — XLA folds them into neighboring
matmuls on TPU; a Pallas path can override via the kernels registry later.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.registry import defop
from ...framework.tensor import Tensor, run_op, no_grad

__all__ = ["layer_norm", "rms_norm", "batch_norm", "instance_norm",
           "group_norm", "local_response_norm", "spectral_norm"]


@defop()
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    # reduce in fp32 for bf16 stability (TPU norm idiom)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@defop()
def rms_norm(x, weight=None, epsilon=1e-6, bias=None, axis=-1):
    """RMSNorm (reference: incubate fused_rms_norm). fp32 accumulation.
    ``axis`` may be an int or tuple (incubate's begin_norm_axis maps to
    ``tuple(range(begin_norm_axis, ndim))``)."""
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=axis, keepdims=True)
    out = (xf * jax.lax.rsqrt(ms + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None):
    """Reference: nn/functional/norm.py batch_norm.

    In training mode batch statistics are used and the running buffers are
    updated in place (the update itself is untracked, like the reference's
    in-place running-stat op). ``momentum`` follows paddle's convention:
    running = momentum * running + (1 - momentum) * batch.
    """
    channel_axis = 1 if data_format.startswith("NC") or x.ndim <= 2 else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    use_batch = training and not use_global_stats

    if use_batch:
        def fn(x_, w_, b_):
            xf = x_.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            var = jnp.var(xf, axis=reduce_axes)
            shape = [1] * x_.ndim
            shape[channel_axis] = -1
            out = (xf - mean.reshape(shape)) * jax.lax.rsqrt(
                var.reshape(shape) + epsilon)
            out = out.astype(x_.dtype)
            if w_ is not None:
                out = out * w_.reshape(shape)
            if b_ is not None:
                out = out + b_.reshape(shape)
            return out, mean, var

        out, mean, var = run_op("batch_norm", fn, (x, weight, bias))
        with no_grad():
            n = 1
            for i in reduce_axes:
                n *= x.shape[i]
            unbiased = var._data * (n / max(n - 1, 1))
            rm_dt = running_mean._data.dtype
            rv_dt = running_var._data.dtype
            running_mean._data = (momentum * running_mean._data
                                  + (1 - momentum) * mean._data).astype(rm_dt)
            running_var._data = (momentum * running_var._data
                                 + (1 - momentum) * unbiased).astype(rv_dt)
        return out

    def fn(x_, rm_, rv_, w_, b_):
        shape = [1] * x_.ndim
        shape[channel_axis] = -1
        xf = x_.astype(jnp.float32)
        out = (xf - rm_.reshape(shape).astype(jnp.float32)) * jax.lax.rsqrt(
            rv_.reshape(shape).astype(jnp.float32) + epsilon)
        out = out.astype(x_.dtype)
        if w_ is not None:
            out = out * w_.reshape(shape)
        if b_ is not None:
            out = out + b_.reshape(shape)
        return out

    return run_op("batch_norm_infer", fn,
                  (x, running_mean, running_var, weight, bias))


@defop()
def instance_norm(x, weight=None, bias=None, epsilon=1e-5,
                  data_format="NCHW"):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(2, x.ndim)) \
        if channel_axis == 1 else tuple(range(1, x.ndim - 1))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=reduce_axes, keepdims=True)
    var = jnp.var(xf, axis=reduce_axes, keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    shape = [1] * x.ndim
    shape[channel_axis] = -1
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@defop()
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-5,
               data_format="NCHW"):
    g = int(num_groups)
    if data_format.startswith("NC"):
        n, c = x.shape[0], x.shape[1]
        spatial = x.shape[2:]
        xg = x.reshape((n, g, c // g) + spatial)
        axes = tuple(range(2, xg.ndim))
        shape = [1, -1] + [1] * len(spatial)
    else:
        n, c = x.shape[0], x.shape[-1]
        spatial = x.shape[1:-1]
        xg = x.reshape((n,) + spatial + (g, c // g))
        axes = tuple(range(1, len(spatial) + 1)) + (xg.ndim - 1,)
        shape = [1] * (len(spatial) + 1) + [-1]
    xf = xg.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = ((xf - mean) * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    out = out.reshape(x.shape)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@defop()
def local_response_norm(x, size=5, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW"):
    channel_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    c = x.shape[channel_axis]
    half = size // 2
    pads = [(0, 0)] * x.ndim
    pads[channel_axis] = (half, size - half - 1)
    sq = jnp.pad(sq, pads)
    window = [1] * x.ndim
    window[channel_axis] = size
    # scalar init keeps the (init, op) monoid recognizable to JAX autodiff
    acc = jax.lax.reduce_window(sq, 0.0, jax.lax.add,
                                tuple(window), (1,) * x.ndim, "VALID")
    # reference normalizes by the window *mean* (avg_pool), not the sum
    return x / jnp.power(k + alpha * acc / size, beta)


@defop()
def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12):
    """Normalize ``weight`` by its largest singular value, estimated by
    power iteration (reference op `spectral_norm`,
    `phi/kernels/impl/spectral_norm_kernel_impl.h`)."""
    w = jnp.moveaxis(weight, int(dim), 0)
    mat = w.reshape(w.shape[0], -1)
    u = jnp.ones((mat.shape[0],), mat.dtype)
    v = jnp.ones((mat.shape[1],), mat.dtype)
    for _ in range(max(int(power_iters), 1)):
        v = mat.T @ u
        v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        u = mat @ v
        u = u / jnp.maximum(jnp.linalg.norm(u), eps)
    sigma = u @ mat @ v
    return weight / jnp.maximum(sigma, eps)
