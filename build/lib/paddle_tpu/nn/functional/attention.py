"""Attention functionals.

Reference: `python/paddle/nn/functional/flash_attention.py` —
``scaled_dot_product_attention`` (:442) and ``flash_attention`` (:147).
Layout follows the reference: [batch, seq_len, num_heads, head_dim].

Dispatch seam: when ``FLAGS_use_pallas_kernels`` is set and a Pallas flash
kernel is registered (paddle_tpu.ops.flash_attention), it is used; otherwise
the naive composition lowers to XLA (which already fuses well on TPU for
moderate sequence lengths).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor, run_op
from ...framework import random as frandom
from ... import flags

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "sdp_kernel"]


def _naive_attention(q, k, v, mask, dropout_p, is_causal, key, scale=None):
    # [B, S, H, D] -> [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    if kh.shape[1] != qh.shape[1]:
        # GQA fallback: broadcast the kv heads across their query group
        # (XLA keeps this as a broadcast feeding the einsum, no HBM copy)
        group = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, group, axis=1)
        vh = jnp.repeat(vh, group, axis=1)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # fp32 softmax accumulation (TPU numerics idiom)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * s
    if is_causal:
        ql, kl = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((ql, kl), dtype=bool), k=kl - ql)
        scores = jnp.where(causal, scores, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -jnp.inf)
        else:
            scores = scores + mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    probs = probs.astype(qh.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Reference: flash_attention.py:442. Inputs [B, S, H, D]."""
    use_pallas = flags.flag("use_pallas_kernels")
    if use_pallas and dropout_p == 0.0:
        from ...ops import flash_attention as fa
        if fa.supported(query, key, value, attn_mask, is_causal):
            from ...incubate import autotune
            if autotune.get_config()["kernel"]["enable"]:
                # measure-once-then-cache (the reference's exhaustive
                # kernel search, phi/kernels/autotune) per shape+causal
                qd = getattr(query, "_data", query)
                kd = getattr(key, "_data", key)
                shape_key = ("sdpa", tuple(qd.shape), tuple(kd.shape),
                             str(qd.dtype), bool(is_causal))
                _, best = autotune.kernel_choice(shape_key, {
                    "pallas": lambda q, k, v: fa.flash_attention(
                        q, k, v, causal=is_causal),
                    "xla": lambda q, k, v: run_op(
                        "scaled_dot_product_attention",
                        lambda q_, k_, v_: _naive_attention(
                            q_, k_, v_, None, 0.0, is_causal, None),
                        (q, k, v)),
                }, (query, key, value))
                return best(query, key, value)
            return fa.flash_attention(query, key, value, attn_mask=attn_mask,
                                      causal=is_causal)
    rng_key = frandom.next_key() if (dropout_p > 0.0 and training) else None
    p = dropout_p if training else 0.0

    def fn(q, k, v, m, rk):
        return _naive_attention(q, k, v, m, p, is_causal, rk)

    return run_op("scaled_dot_product_attention", fn,
                  (query, key, value, attn_mask, rng_key))


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None,
                    rng_name="", training=True, name=None):
    """Reference: flash_attention.py:147. Returns (out, softmax_lse-like
    placeholder) to match the reference's (result, softmax) tuple shape."""
    out = scaled_dot_product_attention(query, key, value, attn_mask=None,
                                       dropout_p=dropout, is_causal=causal,
                                       training=training)
    if return_softmax:
        return out, None
    return out, None


class sdp_kernel:
    """Context manager selecting attention backends (API parity with the
    reference's sdp kernel switches; the real switch is the Pallas flag)."""

    def __init__(self, enable_flash=True, enable_math=True,
                 enable_mem_efficient=True):
        self.enable_flash = enable_flash
        self._prev = None

    def __enter__(self):
        self._prev = flags.flag("use_pallas_kernels")
        flags.set_flags({"use_pallas_kernels": bool(self.enable_flash)})
        return self

    def __exit__(self, *exc):
        flags.set_flags({"use_pallas_kernels": self._prev})
        return False
