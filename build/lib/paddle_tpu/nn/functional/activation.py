"""Activation functionals.

Reference: `python/paddle/nn/functional/activation.py`. Each op is a single
pure jnp function registered through ``@defop`` so the eager tape records one
grad node per activation and XLA fuses it into neighbors under ``jit``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...tensor.registry import defop
from ...framework.tensor import Tensor, run_op
from ...framework import random as frandom

__all__ = [
    "relu", "relu6", "gelu", "silu", "sigmoid", "tanh", "softmax",
    "log_softmax", "leaky_relu", "elu", "selu", "celu", "prelu",
    "hardshrink", "hardsigmoid", "hardswish", "hardtanh", "softplus",
    "softshrink", "softsign", "swish", "mish", "tanhshrink",
    "thresholded_relu", "log_sigmoid", "glu", "gumbel_softmax", "maxout",
    "rrelu", "tanh_shrink",
]


@defop(method=True, inplace_method="relu_")
def relu(x):
    return jnp.maximum(x, 0)


@defop()
def relu6(x):
    return jnp.clip(x, 0, 6)


@defop(method=True)
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=bool(approximate))


@defop()
def silu(x):
    return x * jax.nn.sigmoid(x)


@defop(method=True)
def sigmoid(x):
    return jax.nn.sigmoid(x)


@defop(name="nn_tanh")
def tanh(x):
    return jnp.tanh(x)


@defop(method=True)
def softmax(x, axis=-1, dtype=None):
    out = jax.nn.softmax(x.astype(dtype) if dtype is not None else x,
                         axis=int(axis))
    return out


@defop()
def log_softmax(x, axis=-1, dtype=None):
    return jax.nn.log_softmax(x.astype(dtype) if dtype is not None else x,
                              axis=int(axis))


@defop()
def leaky_relu(x, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


@defop()
def elu(x, alpha=1.0):
    return jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defop()
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@defop()
def celu(x, alpha=1.0):
    return jnp.maximum(x, 0) + jnp.minimum(0, alpha * jnp.expm1(x / alpha))


@defop()
def prelu(x, weight, data_format="NCHW"):
    w = weight
    if w.ndim == 1 and w.shape[0] != 1 and x.ndim > 1:
        # per-channel slope; broadcast across spatial dims
        if data_format.startswith("NC") or x.ndim <= 2:
            shape = [1, -1] + [1] * (x.ndim - 2)
        else:
            shape = [1] * (x.ndim - 1) + [-1]
        w = w.reshape(shape)
    return jnp.where(x >= 0, x, w * x)


@defop()
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0)


@defop()
def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5):
    return jnp.clip(slope * x + offset, 0, 1)


@defop()
def hardswish(x):
    return x * jnp.clip(x + 3, 0, 6) / 6


@defop()
def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@defop(name="nn_softplus")
def softplus(x, beta=1.0, threshold=20.0):
    return jnp.where(x * beta > threshold, x,
                     jnp.logaddexp(x * beta, 0) / beta)


@defop()
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0))


@defop()
def softsign(x):
    return x / (1 + jnp.abs(x))


@defop()
def swish(x):
    return x * jax.nn.sigmoid(x)


@defop()
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@defop()
def tanhshrink(x):
    return x - jnp.tanh(x)


tanh_shrink = tanhshrink


@defop()
def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


@defop()
def log_sigmoid(x):
    return jax.nn.log_sigmoid(x)


@defop()
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=int(axis))
    return a * jax.nn.sigmoid(b)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    """Reference: nn/functional/activation.py gumbel_softmax. Gumbel noise is
    drawn from the framework generator so it is traceable under jit."""
    key = frandom.next_key()

    def fn(x_, key_):
        g = jax.random.gumbel(key_, x_.shape, dtype=x_.dtype)
        y = jax.nn.softmax((x_ + g) / temperature, axis=int(axis))
        if hard:
            idx = jnp.argmax(y, axis=int(axis), keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=int(axis),
                                        inplace=False)
            # straight-through estimator
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y

    return run_op("gumbel_softmax", fn, (x, key))


@defop()
def maxout(x, groups, axis=1):
    axis = int(axis)
    if axis < 0:
        axis += x.ndim
    c = x.shape[axis]
    new_shape = list(x.shape)
    new_shape[axis:axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True):
    if not training:
        return leaky_relu(x, (lower + upper) / 2)
    key = frandom.next_key()

    def fn(x_, key_):
        a = jax.random.uniform(key_, x_.shape, dtype=x_.dtype,
                               minval=lower, maxval=upper)
        return jnp.where(x_ >= 0, x_, a * x_)

    return run_op("rrelu", fn, (x, key))
