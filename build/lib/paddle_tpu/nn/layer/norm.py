"""Normalization layers.

Reference: `python/paddle/nn/layer/norm.py`.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .layers import Layer
from .. import functional as F
from ..initializer import Constant
from ...framework.tensor import Tensor

__all__ = ["LayerNorm", "RMSNorm", "BatchNorm", "BatchNorm1D", "BatchNorm2D",
           "BatchNorm3D", "SyncBatchNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm",
           "SpectralNorm"]


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """RMS normalization (reference: incubate fused_rms_norm / modern LLM
    stacks). Weight only, fp32 accumulation."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr,
                                          is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features],
                                                       jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features],
                                                          jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCL"
                         else data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under GSPMD the batch axis is sharded and XLA
    computes global batch statistics automatically when the reduction spans
    the sharded axis — so SyncBatchNorm == BatchNorm in the compiled path
    (reference: nn/layer/norm.py SyncBatchNorm requires explicit NCCL
    allreduce; the mesh makes that implicit here)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            new.weight, new.bias = layer.weight, layer.bias
            new._buffers = layer._buffers
            return new
        return layer


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False and bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, self.weight, self.bias, self._epsilon,
                               self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Power-iteration spectral normalization of a weight tensor
    (reference: nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal
        self.weight_u = self.create_parameter(
            [h], default_initializer=Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...framework.tensor import run_op
        dim, eps, iters = self._dim, self._epsilon, self._power_iters

        def fn(w, u, v):
            perm = [dim] + [i for i in range(w.ndim) if i != dim]
            mat = jnp.transpose(w, perm).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma

        return run_op("spectral_norm", fn,
                      (weight, self.weight_u, self.weight_v))
