"""The ``Layer`` base class — the model-authoring surface.

Reference: `python/paddle/nn/layer/layers.py:332` (``Layer``): parameter /
buffer / sublayer registries, hooks, ``state_dict``/``set_state_dict``,
train/eval mode, ``apply``, ``to``. TPU-native notes: parameters are eager
``Parameter`` tensors whose payloads are ``jax.Array``s; under
``paddle_tpu.jit`` tracing the same objects carry tracers, so one Layer
definition serves both the eager debug path and the compiled XLA path.
"""

from __future__ import annotations

import collections
from typing import Callable, Iterator

import numpy as np
import jax.numpy as jnp

from ...framework.tensor import Tensor, Parameter
from ...framework import dtype as dtypes
from ..initializer import (Initializer, Constant, _default_weight_init,
                           _default_bias_init)

__all__ = ["Layer", "ParamAttr"]


class ParamAttr:
    """Parameter attribute bundle (reference: `python/paddle/base/param_attr.py`)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        raise TypeError(f"invalid ParamAttr: {attr!r}")


class HookRemoveHelper:
    next_hook_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self._hook_id = HookRemoveHelper.next_hook_id
        HookRemoveHelper.next_hook_id += 1

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """Base class for all NN layers (reference Layer, layers.py:332)."""

    def __init__(self, name_scope=None, dtype=None):
        self.training = True
        self._dtype = dtype or dtypes.get_default_dtype()
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._init_in_dynamic_mode = True

    # -- forward ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # -- registration -------------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Reference: layers.py create_parameter."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        init = attr.initializer or default_initializer or \
            (_default_bias_init() if is_bias else _default_weight_init())
        data = init(shape, dtype)
        p = Parameter(data, name=attr.name, trainable=attr.trainable)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=False, dtype=None):
        t = Tensor(jnp.zeros([], dtype=dtypes.convert_dtype(dtype or self._dtype)))
        t.name = name
        t.persistable = persistable
        return t

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError(f"add_parameter expects Parameter, got "
                            f"{type(parameter).__name__}")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError(f"add_sublayer expects Layer, got "
                            f"{type(sublayer).__name__}")
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        """Reference: layers.py register_buffer — non-parameter state that
        joins state_dict when persistable (e.g. BN running stats)."""
        if tensor is not None and not isinstance(tensor, Tensor):
            raise TypeError("register_buffer expects a Tensor")
        self._buffers[name] = tensor
        if persistable:
            self._non_persistable_buffer_names.discard(name)
        else:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute magic ----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() first")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif params is not None and name in params:
            if value is None:
                params[name] = None
            elif isinstance(value, Tensor):
                params[name].set_value(value)
            else:
                raise TypeError(f"cannot assign {type(value).__name__} to "
                                f"parameter '{name}'")
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                buffers[name].set_value(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d:
                extra.extend(d.keys())
        return list(super().__dir__()) + extra

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        memo = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in memo:
                    continue
                memo.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        memo = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in memo:
                    continue
                memo.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self):
        memo = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in memo:
                memo.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True,
                                         layers_set=layers_set)

    def apply(self, fn: Callable[["Layer"], None]):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def full_name(self):
        return self._name_scope

    # -- mode ---------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._hook_id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._hook_id] = hook
        return helper

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        """Reference: layers.py state_dict — parameters + persistable
        buffers keyed by structured names."""
        destination = destination if destination is not None \
            else collections.OrderedDict()
        for name, p in self.named_parameters():
            destination[structured_name_prefix + name] = p
        for lname, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                key = structured_name_prefix + \
                    (f"{lname}.{bname}" if lname else bname)
                destination[key] = b
        return destination

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Reference: layers.py set_state_dict. Returns (missing, unexpected)
        like the reference's match info."""
        own = self.state_dict()
        missing, matched = [], set()
        for key, target in own.items():
            if key in state_dict:
                value = state_dict[key]
                arr = value._data if isinstance(value, Tensor) else \
                    jnp.asarray(np.asarray(value))
                if tuple(arr.shape) != tuple(target._data.shape):
                    raise ValueError(
                        f"shape mismatch for '{key}': loaded {list(arr.shape)}"
                        f" vs parameter {list(target._data.shape)}")
                target._data = arr.astype(target._data.dtype)
                matched.add(key)
            else:
                missing.append(key)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / device movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        def convert(t):
            if t is None:
                return t
            out = t
            if dtype is not None and jnp.issubdtype(out._data.dtype,
                                                    jnp.floating):
                out._data = out._data.astype(dtypes.convert_dtype(dtype))
            if device is not None:
                from ...device import _resolve_device
                import jax
                out._data = jax.device_put(out._data,
                                           _resolve_device(str(device)))
            return t

        for _, p in self.named_parameters():
            convert(p)
        for _, b in self.named_buffers():
            convert(b)
        if dtype is not None:
            self._dtype = dtypes.convert_dtype(dtype)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    # -- misc ---------------------------------------------------------------
    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            mod_str = repr(l)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra:
            main += extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"
