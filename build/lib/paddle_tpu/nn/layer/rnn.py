"""Recurrent layers: SimpleRNN / LSTM / GRU (+ cells, RNN wrapper).

Reference: `python/paddle/nn/layer/rnn.py` (``SimpleRNNCell:135``,
``LSTMCell``, ``GRUCell``, ``RNN``, ``SimpleRNN``/``LSTM``/``GRU`` with
multi-layer + bidirect). TPU-native mechanics: the time recurrence is ONE
``lax.scan`` per (layer, direction) — static trip count, XLA-schedulable,
differentiable — instead of the reference's per-timestep CUDA kernels /
cuDNN RNN descriptors.

Weight layout matches the reference: ``weight_ih [G*H, I]``,
``weight_hh [G*H, H]``, biases ``[G*H]`` with gate chunk order
i, f, g(cell), o for LSTM and r, z, c for GRU. States are
``[num_layers * num_directions, B, H]``.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.tensor import Parameter, Tensor, run_op
from ...framework import random as frandom
from .layers import Layer
from .. import functional as F

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN",
           "BiRNN", "SimpleRNN", "LSTM", "GRU"]


def _uniform(key, shape, k):
    return jax.random.uniform(key, shape, jnp.float32, -k, k)


# ---------------------------------------------------------------------------
# pure per-step cell math (shared by cells and the scanned networks)
# ---------------------------------------------------------------------------
def _simple_step(x, h, wi, wh, bi, bh, activation):
    z = x @ wi.T + h @ wh.T
    if bi is not None:
        z = z + bi + bh
    return jnp.tanh(z) if activation == "tanh" else jnp.maximum(z, 0.0)


def _lstm_step(x, hc, wi, wh, bi, bh):
    h, c = hc
    z = x @ wi.T + h @ wh.T
    if bi is not None:
        z = z + bi + bh
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    return o * jnp.tanh(c2), c2


def _gru_step(x, h, wi, wh, bi, bh):
    gi = x @ wi.T
    gh = h @ wh.T
    if bi is not None:
        gi = gi + bi
        gh = gh + bh
    ri, zi, ci = jnp.split(gi, 3, axis=-1)
    rh, zh, ch = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ri + rh)
    z = jax.nn.sigmoid(zi + zh)
    c = jnp.tanh(ci + r * ch)
    return (1.0 - z) * c + z * h


# ---------------------------------------------------------------------------
# cells (single step, Tensor-level)
# ---------------------------------------------------------------------------
class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        from ...tensor import creation
        if isinstance(self.state_shape, tuple):
            return tuple(
                creation.full([b] + list(s), init_value, dtype=dtype)
                for s in self.state_shape)
        return creation.full([b] + list(self.state_shape), init_value,
                             dtype=dtype)


def _make_cell_params(cell, input_size, hidden_size, gates, bias=True):
    k = 1.0 / math.sqrt(hidden_size)
    g = gates * hidden_size
    cell.weight_ih = Parameter(_uniform(frandom.next_key(),
                                        (g, input_size), k))
    cell.weight_hh = Parameter(_uniform(frandom.next_key(),
                                        (g, hidden_size), k))
    if bias:
        cell.bias_ih = Parameter(_uniform(frandom.next_key(), (g,), k))
        cell.bias_hh = Parameter(_uniform(frandom.next_key(), (g,), k))
    else:
        cell.bias_ih = None
        cell.bias_hh = None


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        _make_cell_params(self, input_size, hidden_size, 1,
                          bias=bias_ih_attr is not False)

    @property
    def state_shape(self):
        return [self.hidden_size]

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = self.activation
        out = run_op("simple_rnn_cell",
                     lambda x, h, wi, wh, bi, bh: _simple_step(
                         x, h, wi, wh, bi, bh, act),
                     (inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh))
        return out, out


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _make_cell_params(self, input_size, hidden_size, 4,
                          bias=bias_ih_attr is not False)

    @property
    def state_shape(self):
        return ([self.hidden_size], [self.hidden_size])

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def fn(x, h_, c_, wi, wh, bi, bh):
            return _lstm_step(x, (h_, c_), wi, wh, bi, bh)

        h2, c2 = run_op("lstm_cell", fn,
                        (inputs, h, c, self.weight_ih, self.weight_hh,
                         self.bias_ih, self.bias_hh))
        return h2, (h2, c2)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        _make_cell_params(self, input_size, hidden_size, 3,
                          bias=bias_ih_attr is not False)

    @property
    def state_shape(self):
        return [self.hidden_size]

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = run_op("gru_cell", _gru_step,
                     (inputs, states, self.weight_ih, self.weight_hh,
                      self.bias_ih, self.bias_hh))
        return out, out


# ---------------------------------------------------------------------------
# scanned single-direction runner
# ---------------------------------------------------------------------------
def _scan_layer(mode, activation, reverse):
    """Returns a pure fn (x [B,T,I], h0.., weights..) -> (out [B,T,H],
    final states)."""

    def fn(x, h0, c0, wi, wh, bi, bh, seq_len):
        xs = jnp.swapaxes(x, 0, 1)               # [T, B, I]
        T = xs.shape[0]
        if reverse:
            xs = xs[::-1]

        def step(carry, inp):
            xt, t = inp
            if mode == "lstm":
                h, c = carry
                h2, c2 = _lstm_step(xt, (h, c), wi, wh, bi, bh)
            elif mode == "gru":
                h = carry
                h2 = _gru_step(xt, h, wi, wh, bi, bh)
                c2 = None
            else:
                h = carry
                h2 = _simple_step(xt, h, wi, wh, bi, bh, activation)
                c2 = None
            if seq_len is not None:
                # frozen beyond each sequence's length
                tt = (T - 1 - t) if reverse else t
                valid = (tt < seq_len)[:, None]
                if mode == "lstm":
                    h2 = jnp.where(valid, h2, h)
                    c2 = jnp.where(valid, c2, c)
                else:
                    h2 = jnp.where(valid, h2, h)
            carry2 = (h2, c2) if mode == "lstm" else h2
            return carry2, h2

        init = (h0, c0) if mode == "lstm" else h0
        carry, ys = jax.lax.scan(step, init,
                                 (xs, jnp.arange(T, dtype=jnp.int32)))
        if reverse:
            ys = ys[::-1]
        out = jnp.swapaxes(ys, 0, 1)             # [B, T, H]
        if mode == "lstm":
            return out, carry[0], carry[1]
        return out, carry

    return fn


class RNN(Layer):
    """Runs a cell over time (reference rnn.py RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs
        if self.time_major:
            from ...tensor import manipulation as M
            x = M.transpose(x, [1, 0, 2])
        mode = {"SimpleRNNCell": "simple", "LSTMCell": "lstm",
                "GRUCell": "gru"}.get(type(self.cell).__name__)
        if mode is None:
            return self._forward_generic(x, initial_states, sequence_length)
        act = getattr(self.cell, "activation", "tanh")
        fn = _scan_layer(mode, act, self.is_reverse)
        if initial_states is None:
            initial_states = self.cell.get_initial_states(x)
        if mode == "lstm":
            h0, c0 = initial_states
        else:
            h0, c0 = initial_states, None
        outs = run_op("rnn_scan", fn,
                      (x, h0, c0, self.cell.weight_ih,
                       self.cell.weight_hh, self.cell.bias_ih,
                       self.cell.bias_hh, sequence_length))
        if mode == "lstm":
            out, h, c = outs
            states = (h, c)
        else:
            out, states = outs
        if self.time_major:
            from ...tensor import manipulation as M
            out = M.transpose(out, [1, 0, 2])
        return out, states

    def _forward_generic(self, x, initial_states, sequence_length):
        # python-loop fallback for user-defined cells
        T = x.shape[1]
        states = initial_states
        if states is None:
            states = self.cell.get_initial_states(x[:, 0])
        ys = []
        prev_y = None
        rng = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in rng:
            y, new_states = self.cell(x[:, t], states)
            if sequence_length is not None:
                # same freeze-past-length semantics as the scanned path
                valid = (sequence_length > t).astype(y.dtype) \
                    .reshape([-1, 1])

                def mix(new, old):
                    return new * valid + old * (1.0 - valid)

                if isinstance(new_states, (tuple, list)):
                    new_states = type(new_states)(
                        mix(n, o) for n, o in zip(new_states, states))
                else:
                    new_states = mix(new_states, states)
                if prev_y is not None:
                    y = mix(y, prev_y)
            states = new_states
            prev_y = y
            ys.append(y)
        if self.is_reverse:
            ys = ys[::-1]
        from ...tensor import manipulation as M
        out = M.stack(ys, axis=1)
        return out, states


class BiRNN(Layer):
    """Forward + backward cells, outputs concatenated (reference BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        st_f = st_b = None
        if initial_states is not None:
            st_f, st_b = initial_states
        out_f, s_f = self.rnn_fw(inputs, st_f, sequence_length)
        out_b, s_b = self.rnn_bw(inputs, st_b, sequence_length)
        from ...tensor import manipulation as M
        return M.concat([out_f, out_b], axis=-1), (s_f, s_b)


# ---------------------------------------------------------------------------
# multi-layer networks
# ---------------------------------------------------------------------------
class _RNNBase(Layer):
    MODE = "simple"
    GATES = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unknown direction {direction!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirect = direction != "forward"
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        ndir = 2 if self.bidirect else 1
        from .container import LayerList
        cells = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * ndir
            for _ in range(ndir):
                cells.append(self._make_cell(in_sz, hidden_size))
        self.cells = LayerList(cells)

    def _make_cell(self, in_sz, hidden):
        if self.MODE == "lstm":
            return LSTMCell(in_sz, hidden)
        if self.MODE == "gru":
            return GRUCell(in_sz, hidden)
        return SimpleRNNCell(in_sz, hidden, activation=self.activation)

    def _zero_state(self, b, dtype):
        from ...tensor import creation
        ndir = 2 if self.bidirect else 1
        n = self.num_layers * ndir
        return creation.zeros([n, b, self.hidden_size], dtype=dtype)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs
        if self.time_major:
            from ...tensor import manipulation as M
            x = M.transpose(x, [1, 0, 2])
        b = x.shape[0]
        dtype = "float32"
        is_lstm = self.MODE == "lstm"
        if initial_states is None:
            h_all = self._zero_state(b, dtype)
            c_all = self._zero_state(b, dtype) if is_lstm else None
        else:
            if is_lstm:
                h_all, c_all = initial_states
            else:
                h_all, c_all = initial_states, None

        ndir = 2 if self.bidirect else 1
        finals_h, finals_c = [], []
        out = x
        for layer in range(self.num_layers):
            outs_dir = []
            for d in range(ndir):
                idx = layer * ndir + d
                cell = self.cells[idx]
                fn = _scan_layer(self.MODE, self.activation, d == 1)
                h0 = h_all[idx]
                c0 = c_all[idx] if is_lstm else None
                res = run_op("rnn_scan", fn,
                             (out, h0, c0, cell.weight_ih, cell.weight_hh,
                              cell.bias_ih, cell.bias_hh, sequence_length))
                if is_lstm:
                    o, h, c = res
                    finals_c.append(c)
                else:
                    o, h = res
                finals_h.append(h)
                outs_dir.append(o)
            if ndir == 2:
                from ...tensor import manipulation as M
                out = M.concat(outs_dir, axis=-1)
            else:
                out = outs_dir[0]
            if self.dropout and layer < self.num_layers - 1 \
                    and self.training:
                out = F.dropout(out, p=self.dropout, training=True)
        from ...tensor import manipulation as M
        h_final = M.stack(finals_h, axis=0)
        if self.time_major:
            out = M.transpose(out, [1, 0, 2])
        if is_lstm:
            c_final = M.stack(finals_c, axis=0)
            return out, (h_final, c_final)
        return out, h_final


class SimpleRNN(_RNNBase):
    MODE = "simple"


class LSTM(_RNNBase):
    MODE = "lstm"


class GRU(_RNNBase):
    MODE = "gru"
