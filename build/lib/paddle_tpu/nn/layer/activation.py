"""Activation layers. Reference: `python/paddle/nn/layer/activation.py`."""

from __future__ import annotations

from .layers import Layer
from .. import functional as F
from ..initializer import Constant

__all__ = ["ReLU", "ReLU6", "GELU", "SiLU", "Sigmoid", "Tanh", "Softmax",
           "LogSoftmax", "LeakyReLU", "ELU", "SELU", "CELU", "PReLU",
           "Hardshrink", "Hardsigmoid", "Hardswish", "Hardtanh", "Softplus",
           "Softshrink", "Softsign", "Swish", "Mish", "Tanhshrink",
           "ThresholdedReLU", "LogSigmoid", "GLU", "Maxout", "RReLU"]


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = dict(fixed)
            sig_names = _sigs.get(fn_name, [])
            for n, v in zip(sig_names, args):
                self._kwargs[n] = v
            for k, v in kwargs.items():
                if k != "name":
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fn_name)(x, **self._kwargs)
    _Act.__name__ = fn_name
    return _Act


_sigs = {
    "relu": [], "relu6": [], "silu": [], "sigmoid": [], "tanh": [],
    "gelu": ["approximate"],
    "softmax": ["axis"], "log_softmax": ["axis"],
    "leaky_relu": ["negative_slope"], "elu": ["alpha"], "selu": [],
    "celu": ["alpha"], "hardshrink": ["threshold"], "hardsigmoid": [],
    "hardswish": [], "hardtanh": ["min", "max"],
    "softplus": ["beta", "threshold"], "softshrink": ["threshold"],
    "softsign": [], "swish": [], "mish": [], "tanhshrink": [],
    "thresholded_relu": ["threshold", "value"], "log_sigmoid": [],
    "glu": ["axis"], "maxout": ["groups", "axis"],
}

ReLU = _simple("relu")
ReLU6 = _simple("relu6")
GELU = _simple("gelu")
SiLU = _simple("silu")
Sigmoid = _simple("sigmoid")
Tanh = _simple("tanh")
Softmax = _simple("softmax")
LogSoftmax = _simple("log_softmax")
LeakyReLU = _simple("leaky_relu")
ELU = _simple("elu")
SELU = _simple("selu")
CELU = _simple("celu")
Hardshrink = _simple("hardshrink")
Hardsigmoid = _simple("hardsigmoid")
Hardswish = _simple("hardswish")
Hardtanh = _simple("hardtanh")
Softplus = _simple("softplus")
Softshrink = _simple("softshrink")
Softsign = _simple("softsign")
Swish = _simple("swish")
Mish = _simple("mish")
Tanhshrink = _simple("tanhshrink")
ThresholdedReLU = _simple("thresholded_relu")
LogSigmoid = _simple("log_sigmoid")
GLU = _simple("glu")
Maxout = _simple("maxout")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
