"""Weight initializers.

Reference: `python/paddle/nn/initializer/` (Constant/Normal/Uniform/Xavier/
Kaiming/TruncatedNormal/Assign). TPU-native design: an initializer is a pure
function of (PRNG key, shape, dtype) -> jax array — keys come from the
framework Generator so initialization is reproducible and, under ``jit``
tracing, fully functional.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework import random as frandom
from ...framework.tensor import Tensor
from ...framework import dtype as dtypes

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain",
    "set_global_initializer",
]


def calculate_gain(nonlinearity: str, param=None) -> float:
    """Reference: `python/paddle/nn/initializer/initializer.py` gain table."""
    table = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0), "selu": 3.0 / 4.0,
    }
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity in table:
        return table[nonlinearity]
    raise ValueError(f"unsupported nonlinearity: {nonlinearity}")


def _fan_in_fan_out(shape):
    """Fan computation matching the reference's convention: for a 2-D weight
    of shape [in, out] (paddle Linear stores W as [in_features, out_features]),
    fan_in = shape[0]; conv weights are [out_c, in_c, *k]."""
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


class Initializer:
    def __call__(self, shape, dtype=None):
        dtype = dtypes.convert_dtype(dtype) or dtypes.get_default_dtype()
        key = frandom.next_key()
        return self._generate(key, tuple(int(s) for s in shape), dtype)

    def _generate(self, key, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def _generate(self, key, shape, dtype):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    """Truncated to [mean - a*std, mean + b*std] (default 2 std)."""

    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _generate(self, key, shape, dtype):
        x = jax.random.truncated_normal(key, self.a, self.b, shape, jnp.float32)
        return (x * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def _generate(self, key, shape, dtype):
        return jax.random.uniform(key, shape, jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, key, shape, dtype):
        fi, fo = _fan_in_fan_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, key, shape, dtype):
        fi, fo = _fan_in_fan_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, key, shape, dtype):
        fi, _ = _fan_in_fan_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope) \
            if self.nonlinearity == "leaky_relu" else calculate_gain(self.nonlinearity)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def _generate(self, key, shape, dtype):
        fi, _ = _fan_in_fan_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope) \
            if self.nonlinearity == "leaky_relu" else calculate_gain(self.nonlinearity)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        if isinstance(value, Tensor):
            value = value._data
        self.value = np.asarray(value)

    def _generate(self, key, shape, dtype):
        v = jnp.asarray(self.value, dtype=dtype)
        if tuple(v.shape) != tuple(shape):
            raise ValueError(
                f"Assign initializer shape {v.shape} != parameter shape {shape}")
        return v


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def _generate(self, key, shape, dtype):
        if len(shape) < 2:
            raise ValueError("Orthogonal init needs >=2 dims")
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        q = jax.random.orthogonal(key, max(rows, cols), dtype=jnp.float32)
        q = q[:rows, :cols]
        return (self.gain * q.reshape(shape)).astype(dtype)


class Dirac(Initializer):
    """Identity-preserving conv init (reference nn/initializer/dirac.py)."""

    def __init__(self, groups=1):
        self.groups = groups

    def _generate(self, key, shape, dtype):
        if len(shape) < 3:
            raise ValueError("Dirac init needs a conv weight (>=3 dims)")
        out_c, in_c = shape[0], shape[1]
        w = np.zeros(shape, dtype=np.float32)
        centers = [s // 2 for s in shape[2:]]
        min_c = min(out_c // self.groups, in_c)
        for g in range(self.groups):
            for i in range(min_c):
                idx = (g * (out_c // self.groups) + i, i) + tuple(centers)
                w[idx] = 1.0
        return jnp.asarray(w, dtype=dtype)


_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Reference: `python/paddle/nn/initializer/__init__.py`
    set_global_initializer."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _default_weight_init():
    return _global_weight_init or XavierNormal()


def _default_bias_init():
    return _global_bias_init or Constant(0.0)
