"""Gradient clipping.

Reference: `python/paddle/nn/clip.py` (ClipGradByGlobalNorm / ByNorm /
ByValue). Clips are applied by the optimizer before the update step; each
strategy maps a list of (param, grad) pairs to clipped grads. The global-norm
clip computes one fused norm over all grads — a single XLA reduction under
jit.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor, no_grad

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm",
           "clip_grad_norm_", "clip_grad_value_"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    @no_grad()
    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max),
                                  stop_gradient=True)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    @no_grad()
    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = jnp.linalg.norm(g._data.astype(jnp.float32))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                1.0)
            out.append((p, Tensor((g._data.astype(jnp.float32)
                                   * scale).astype(g._data.dtype),
                                  stop_gradient=True)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    @no_grad()
    def _clip(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                continue
            sq.append(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._data.astype(jnp.float32)
                                   * scale).astype(g._data.dtype),
                                  stop_gradient=True)))
        return out


@no_grad()
def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """torch-style utility the reference also ships
    (`python/paddle/nn/utils/clip_grad_norm_.py`)."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros([], jnp.float32))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g._data.astype(jnp.float32)),
                                  norm_type)) for g in grads),
            1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = (p.grad._data.astype(jnp.float32)
                            * scale).astype(p.grad._data.dtype)
    return Tensor(total)


@no_grad()
def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
