"""Token-corpus feed: native C++ prefetcher with a numpy fallback.

``TokenFeed(path, sample_elems, batch_size)`` iterates ``[batch,
sample_elems]`` numpy batches over a flat binary corpus of fixed-size
samples — the host-side input path for pretraining recipes
(`examples/llama_pretrain.py`). When the native library is available
(`paddle_tpu/native/src/data_feed.cc` — the analog of the reference's
C++ feed threads, `fluid/framework/data_feed.cc`), batches are filled by
a C++ prefetch thread over an mmap; otherwise :class:`PyTokenFeed`
serves the same contract from ``np.memmap`` synchronously.
"""

from __future__ import annotations

import numpy as np

from .. import native as _native

__all__ = ["TokenFeed", "PyTokenFeed"]


class PyTokenFeed:
    """Pure-numpy fallback with identical iteration semantics to
    :class:`paddle_tpu.native.TokenFeed` (same per-epoch permutation is
    NOT guaranteed — the native feed shuffles with C++ mt19937 — but the
    visit-each-sample-once / drop-last contract is)."""

    def __init__(self, path, sample_elems, batch_size, dtype=np.int32,
                 shuffle=True, seed=0, prefetch_depth=4, epochs=-1):
        self.dtype = np.dtype(dtype)
        self.sample_elems = int(sample_elems)
        self.batch_size = int(batch_size)
        data = np.memmap(path, dtype=self.dtype, mode="r")
        n = data.size // self.sample_elems
        if n < self.batch_size:
            raise ValueError(
                f"TokenFeed: cannot open {path!r} (too small for one "
                f"batch of {batch_size} x {sample_elems} {self.dtype})")
        self._data = data[:n * self.sample_elems].reshape(
            n, self.sample_elems)
        self.shuffle, self.seed = shuffle, seed
        self.epochs = epochs
        self._epoch = 0
        self._step = 0
        self._order = self._epoch_order()

    @property
    def num_samples(self):
        return self._data.shape[0]

    @property
    def batches_per_epoch(self):
        return self.num_samples // self.batch_size

    def _epoch_order(self):
        if not self.shuffle:
            return np.arange(self.num_samples)
        return np.random.RandomState(
            self.seed + self._epoch).permutation(self.num_samples)

    def __iter__(self):
        return self

    def __next__(self):
        if self._step >= self.batches_per_epoch:
            self._epoch += 1
            if self.epochs > 0 and self._epoch >= self.epochs:
                raise StopIteration
            self._step = 0
            self._order = self._epoch_order()
        idx = self._order[self._step * self.batch_size:
                          (self._step + 1) * self.batch_size]
        self._step += 1
        return np.ascontiguousarray(self._data[idx])

    def close(self):
        pass


def TokenFeed(path, sample_elems, batch_size, dtype=np.int32, shuffle=True,
              seed=0, prefetch_depth=4, epochs=-1):
    """Factory: the native prefetching feed when buildable, else the
    numpy fallback. Both yield ``[batch_size, sample_elems]`` arrays."""
    cls = _native.TokenFeed if _native.available() else PyTokenFeed
    return cls(path, sample_elems, batch_size, dtype=dtype, shuffle=shuffle,
               seed=seed, prefetch_depth=prefetch_depth, epochs=epochs)
