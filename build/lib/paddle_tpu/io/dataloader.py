"""DataLoader.

Reference: `python/paddle/io/dataloader/dataloader_iter.py` +
`python/paddle/io/reader.py` (``DataLoader``). TPU-native notes: the loader
yields host numpy batches; device transfer happens at the jit boundary
(one H2D per step, overlappable). ``num_workers>0`` uses a thread pool
prefetcher — on TPU hosts the heavy lifting (decode/augment) is numpy in
threads; there is no CUDA pinned-memory concept to manage.
"""

from __future__ import annotations

import queue
import threading

import numpy as np

from ..framework.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, SequenceSampler, RandomSampler

__all__ = ["DataLoader", "default_collate_fn"]


def default_collate_fn(batch):
    """Stack samples into batch arrays (reference:
    `io/dataloader/collate.py` ``default_collate_fn``)."""
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack(batch, axis=0)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._data) for s in batch], axis=0)
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(fields))
                            for fields in zip(*batch))
    raise TypeError(f"batch data can't be collated: {type(sample)}")


class _PrefetchIter:
    """Thread-pool prefetching iterator (num_workers > 0)."""

    def __init__(self, loader, index_iter):
        self._loader = loader
        self._index_queue = queue.Queue()
        self._data_queue = queue.Queue(maxsize=max(
            2, loader.num_workers * loader.prefetch_factor))
        self._n_batches = 0
        for i, idxs in enumerate(index_iter):
            self._index_queue.put((i, idxs))
            self._n_batches += 1
        self._results = {}
        self._next = 0
        self._stop = threading.Event()
        self._workers = [
            threading.Thread(target=self._worker_loop, daemon=True)
            for _ in range(loader.num_workers)]
        for w in self._workers:
            w.start()

    def _worker_loop(self):
        while not self._stop.is_set():
            try:
                i, idxs = self._index_queue.get_nowait()
            except queue.Empty:
                return
            try:
                item = (i, self._loader._fetch(idxs), None)
            except Exception as e:  # propagate to consumer
                item = (i, None, e)
            # bounded put must stay interruptible: a worker stuck in a
            # blocking put outlives an abandoned iterator and crashes
            # interpreter teardown (runtime destructors vs live threads)
            while not self._stop.is_set():
                try:
                    self._data_queue.put(item, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def close(self):
        """Stop workers; safe to call repeatedly (StopIteration, __del__,
        and abandoned partially-consumed iterators all land here)."""
        self._stop.set()
        while True:  # unblock any worker parked on a full queue
            try:
                self._data_queue.get_nowait()
            except queue.Empty:
                break
        for w in self._workers:
            if w.is_alive():
                w.join(timeout=1.0)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __iter__(self):
        return self

    def __next__(self):
        if self._next >= self._n_batches:
            self.close()
            raise StopIteration
        while self._next not in self._results:
            i, batch, err = self._data_queue.get()
            if err is not None:
                self.close()
                raise err
            self._results[i] = batch
        out = self._results.pop(self._next)
        self._next += 1
        return out


class DataLoader:
    """Reference: `python/paddle/io/reader.py` ``DataLoader``."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = int(num_workers)
        self.prefetch_factor = prefetch_factor
        self.return_list = return_list
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            if batch_sampler is not None or shuffle:
                raise ValueError(
                    "IterableDataset does not support batch_sampler/shuffle")
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
            self.drop_last = batch_sampler.drop_last
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
                self.drop_last = False
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
                self.batch_size = batch_size
                self.drop_last = drop_last

    def _fetch(self, indices):
        return self.collate_fn([self.dataset[i] for i in indices])

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            if self.batch_size is None:
                yield sample
                continue
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def __iter__(self):
        if self._iterable_mode:
            return self._iter_iterable()
        if self.batch_sampler is None:  # unbatched indexing
            return (self.dataset[i] for i in range(len(self.dataset)))
        if self.num_workers > 0:
            return _PrefetchIter(self, iter(self.batch_sampler))
        return (self._fetch(idxs) for idxs in self.batch_sampler)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)
