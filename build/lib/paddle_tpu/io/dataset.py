"""Datasets.

Reference: `python/paddle/io/dataloader/dataset.py:25` (``Dataset``,
``IterableDataset``, ``TensorDataset``, ``ComposeDataset``,
``ChainDataset``, ``Subset``, ``random_split``, ``ConcatDataset``).
"""

from __future__ import annotations

import bisect
import itertools

import numpy as np

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split"]


class Dataset:
    """Map-style dataset: implement ``__getitem__`` and ``__len__``."""

    def __getitem__(self, idx):
        raise NotImplementedError(
            "'{}' not implement in class {}".format("__getitem__",
                                                    self.__class__.__name__))

    def __len__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format("__len__",
                                                    self.__class__.__name__))


class IterableDataset(Dataset):
    """Iterable-style dataset: implement ``__iter__``."""

    def __iter__(self):
        raise NotImplementedError(
            "'{}' not implement in class {}".format("__iter__",
                                                    self.__class__.__name__))

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset does not support len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lengths = {len(t) for t in tensors}
        if len(lengths) > 1:
            raise ValueError("tensors must have the same first dimension")
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    """Zip datasets: sample i is the concatenation of each dataset's sample i."""

    def __init__(self, datasets):
        if not datasets:
            raise ValueError("datasets must not be empty")
        self.datasets = list(datasets)
        lengths = {len(d) for d in self.datasets}
        if len(lengths) > 1:
            raise ValueError("datasets must have the same length")

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            s = d[idx]
            sample.extend(s if isinstance(s, (list, tuple)) else [s])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        return itertools.chain(*self.datasets)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("datasets should not be an empty iterable")
        sizes = [len(d) for d in self.datasets]
        self.cumulative_sizes = list(itertools.accumulate(sizes))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            if -idx > len(self):
                raise ValueError("index out of range")
            idx = len(self) + idx
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        if ds_idx > 0:
            idx = idx - self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    """Reference: dataset.py ``random_split``; fractional lengths supported."""
    if all(isinstance(l, float) for l in lengths) and \
            abs(sum(lengths) - 1.0) < 1e-6:
        n = len(dataset)
        sizes = [int(np.floor(n * frac)) for frac in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != len(dataset):
        raise ValueError(
            "Sum of input lengths does not equal the length of the dataset!")
    rng = np.random.default_rng(
        generator if isinstance(generator, (int, type(None))) else None)
    perm = rng.permutation(sum(lengths)).tolist()
    out, offset = [], 0
    for length in lengths:
        out.append(Subset(dataset, perm[offset:offset + length]))
        offset += length
    return out
