"""Samplers.

Reference: `python/paddle/io/dataloader/sampler.py` (Sampler,
SequenceSampler, RandomSampler, WeightedRandomSampler,
SubsetRandomSampler) and `batch_sampler.py` (BatchSampler,
DistributedBatchSampler).
"""

from __future__ import annotations

import numpy as np

__all__ = ["Sampler", "SequenceSampler", "RandomSampler",
           "WeightedRandomSampler", "SubsetRandomSampler", "BatchSampler",
           "DistributedBatchSampler"]


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator
        if not replacement and num_samples is not None and \
                num_samples > len(data_source):
            raise ValueError(
                "num_samples cannot exceed dataset size without replacement")

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None \
            else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if isinstance(self.generator, int):
            seed = self.generator
        else:
            # derive from the framework generator so paddle.seed() governs
            # shuffle order (the reference shuffles from the global
            # generator; OS entropy here would make runs unreproducible)
            import jax
            from ..framework import random as frandom
            seed = int(jax.random.randint(frandom.next_key(), (), 0,
                                          2 ** 31 - 1))
        rng = np.random.default_rng(seed)
        if self.replacement:
            yield from rng.integers(0, n, size=self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[:self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        super().__init__(None)
        self.weights = np.asarray(weights, dtype=np.float64)
        if (self.weights < 0).any():
            raise ValueError("weights must be non-negative")
        self.num_samples = num_samples
        self.replacement = replacement
        if not replacement and num_samples > len(self.weights):
            raise ValueError(
                "num_samples cannot exceed len(weights) without replacement")

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), size=self.num_samples,
                               replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices):
        super().__init__(None)
        self.indices = list(indices)

    def __iter__(self):
        for i in np.random.permutation(len(self.indices)).tolist():
            yield self.indices[i]

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    """Reference: batch_sampler.py ``BatchSampler``."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if dataset is None and sampler is None:
            raise ValueError("either dataset or sampler must be given")
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)
        if batch_size <= 0:
            raise ValueError("batch_size should be a positive integer")
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.shuffle = shuffle

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sliced batch sampler (reference: batch_sampler.py
    ``DistributedBatchSampler``): pads the index list so every rank sees the
    same number of batches, then strides by rank."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        if batch_size <= 0:
            raise ValueError("batch_size should be a positive integer")
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import env
            num_replicas = num_replicas or env.get_world_size()
            rank = rank if rank is not None else env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        indices = list(range(len(self.dataset)))
        if self.shuffle:
            rng = np.random.default_rng(self.epoch)
            indices = rng.permutation(len(indices)).tolist()
        # pad to be evenly divisible across ranks
        indices += indices[:(self.total_size - len(indices))]
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
