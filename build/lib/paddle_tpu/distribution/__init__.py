"""``paddle.distribution`` — probability distributions.

Reference: `python/paddle/distribution/` (Distribution base
`distribution.py`, ~25 concrete families, `kl.py` registered
kl_divergence pairs, `transform.py` bijectors +
`transformed_distribution.py`). TPU-native mechanics: sampling draws
typed jax.random primitives keyed from the framework generator (so
``paddle.seed`` governs sampling, and under ``jit.to_static`` the key is
an input of the compiled program); densities are pure jnp math recorded
on the autograd tape, so ``log_prob`` is differentiable in the
distribution parameters (rsample via reparameterization where it exists).
"""

from __future__ import annotations

import math

import jax
import numpy as np
import jax.numpy as jnp
from jax.scipy import special as jsp

from ..framework.tensor import Tensor, run_op
from ..framework import random as frandom

__all__ = ["Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
           "Beta", "Gamma", "Exponential", "Laplace", "LogNormal",
           "Gumbel", "Geometric", "Poisson", "Cauchy", "Multinomial",
           "Dirichlet", "kl_divergence", "register_kl",
           "TransformedDistribution", "Transform", "AffineTransform",
           "ExpTransform", "SigmoidTransform", "TanhTransform"]


def _t(x, dtype=jnp.float32):
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, dtype))


def _shape(sample_shape, batch_shape):
    return tuple(int(s) for s in sample_shape) + tuple(batch_shape)


class Distribution:
    """Base (reference distribution.py Distribution)."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        from ..framework.tensor import no_grad
        with no_grad():
            return self.rsample(shape).detach()

    def rsample(self, shape=()):
        raise NotImplementedError(
            f"{type(self).__name__} has no reparameterized sampler")

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return self.log_prob(value).exp()

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


# ---------------------------------------------------------------------------
# continuous, reparameterizable
# ---------------------------------------------------------------------------
class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc._data.shape,
                                              self.scale._data.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    @property
    def stddev(self):
        return self.scale

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(loc, scale):
            eps = jax.random.normal(key, out_shape, jnp.float32)
            return loc + scale * eps

        return run_op("normal_rsample", fn, (self.loc, self.scale))

    def log_prob(self, value):
        value = _t(value)

        def fn(v, loc, scale):
            var = scale ** 2
            return -((v - loc) ** 2) / (2 * var) - jnp.log(scale) \
                - 0.5 * math.log(2 * math.pi)

        return run_op("normal_log_prob", fn, (value, self.loc, self.scale))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + self.scale.log()

    def cdf(self, value):
        value = _t(value)
        return run_op("normal_cdf",
                      lambda v, l, s: 0.5 * (1 + jsp.erf(
                          (v - l) / (s * math.sqrt(2)))),
                      (value, self.loc, self.scale))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        self._base = Normal(loc, scale)
        super().__init__(self._base.batch_shape)

    @property
    def mean(self):
        return (self.loc + 0.5 * self.scale * self.scale).exp()

    @property
    def variance(self):
        s2 = self.scale * self.scale
        return ((s2).exp() - 1.0) * (2.0 * self.loc + s2).exp()

    def rsample(self, shape=()):
        return self._base.rsample(shape).exp()

    def log_prob(self, value):
        value = _t(value)
        return self._base.log_prob(value.log()) - value.log()

    def entropy(self):
        return self._base.entropy() + self.loc


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(jnp.broadcast_shapes(self.low._data.shape,
                                              self.high._data.shape))

    @property
    def mean(self):
        return (self.low + self.high) / 2

    @property
    def variance(self):
        d = self.high - self.low
        return d * d / 12

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(low, high):
            u = jax.random.uniform(key, out_shape, jnp.float32)
            return low + (high - low) * u

        return run_op("uniform_rsample", fn, (self.low, self.high))

    def log_prob(self, value):
        value = _t(value)

        def fn(v, low, high):
            inside = (v >= low) & (v < high)
            return jnp.where(inside, -jnp.log(high - low), -jnp.inf)

        return run_op("uniform_log_prob", fn, (value, self.low, self.high))

    def entropy(self):
        return (self.high - self.low).log()


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate._data.shape)

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return 1.0 / (self.rate * self.rate)

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(rate):
            return jax.random.exponential(key, out_shape,
                                          jnp.float32) / rate

        return run_op("exponential_rsample", fn, (self.rate,))

    def log_prob(self, value):
        value = _t(value)
        return run_op(
            "exponential_log_prob",
            lambda v, r: jnp.where(v >= 0, jnp.log(r) - r * v, -jnp.inf),
            (value, self.rate))

    def entropy(self):
        return 1.0 - self.rate.log()


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc._data.shape,
                                              self.scale._data.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2.0 * self.scale * self.scale

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(loc, scale):
            return loc + scale * jax.random.laplace(key, out_shape,
                                                    jnp.float32)

        return run_op("laplace_rsample", fn, (self.loc, self.scale))

    def log_prob(self, value):
        value = _t(value)
        return run_op(
            "laplace_log_prob",
            lambda v, l, s: -jnp.abs(v - l) / s - jnp.log(2 * s),
            (value, self.loc, self.scale))

    def entropy(self):
        return 1.0 + (2.0 * self.scale).log()


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc._data.shape,
                                              self.scale._data.shape))

    @property
    def mean(self):
        return self.loc + jnp.euler_gamma * self.scale

    @property
    def variance(self):
        return (math.pi ** 2 / 6.0) * self.scale * self.scale

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(loc, scale):
            return loc + scale * jax.random.gumbel(key, out_shape,
                                                   jnp.float32)

        return run_op("gumbel_rsample", fn, (self.loc, self.scale))

    def log_prob(self, value):
        value = _t(value)

        def fn(v, loc, scale):
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)

        return run_op("gumbel_log_prob", fn, (value, self.loc, self.scale))

    def entropy(self):
        return self.scale.log() + (1.0 + jnp.euler_gamma)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc._data.shape,
                                              self.scale._data.shape))

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(loc, scale):
            return loc + scale * jax.random.cauchy(key, out_shape,
                                                   jnp.float32)

        return run_op("cauchy_rsample", fn, (self.loc, self.scale))

    def log_prob(self, value):
        value = _t(value)

        def fn(v, loc, scale):
            z = (v - loc) / scale
            return -jnp.log(math.pi * scale * (1 + z * z))

        return run_op("cauchy_log_prob", fn, (value, self.loc, self.scale))

    def entropy(self):
        return (4.0 * math.pi * self.scale).log()


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha._data.shape,
                                              self.beta._data.shape))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s * s * (s + 1.0))

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(a, b):
            return jax.random.beta(key, a, b, out_shape, jnp.float32)

        return run_op("beta_rsample", fn, (self.alpha, self.beta))

    def log_prob(self, value):
        value = _t(value)

        def fn(v, a, b):
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) \
                - (jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b))

        return run_op("beta_log_prob", fn, (value, self.alpha, self.beta))

    def entropy(self):
        def fn(a, b):
            total = a + b
            return (jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(total)
                    - (a - 1) * jsp.digamma(a) - (b - 1) * jsp.digamma(b)
                    + (total - 2) * jsp.digamma(total))

        return run_op("beta_entropy", fn, (self.alpha, self.beta))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _t(concentration)
        self.rate = _t(rate)
        super().__init__(jnp.broadcast_shapes(
            self.concentration._data.shape, self.rate._data.shape))

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / (self.rate * self.rate)

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(a, r):
            return jax.random.gamma(key, a, out_shape, jnp.float32) / r

        return run_op("gamma_rsample", fn, (self.concentration, self.rate))

    def log_prob(self, value):
        value = _t(value)

        def fn(v, a, r):
            return a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v \
                - jsp.gammaln(a)

        return run_op("gamma_log_prob", fn,
                      (value, self.concentration, self.rate))

    def entropy(self):
        def fn(a, r):
            return a - jnp.log(r) + jsp.gammaln(a) \
                + (1 - a) * jsp.digamma(a)

        return run_op("gamma_entropy", fn, (self.concentration, self.rate))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        shape = self.concentration._data.shape
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return self.concentration / self.concentration.sum(
            axis=-1, keepdim=True)

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape) \
            + tuple(self.event_shape)

        def fn(a):
            g = jax.random.gamma(key, jnp.broadcast_to(a, out_shape),
                                 dtype=jnp.float32)
            return g / jnp.sum(g, axis=-1, keepdims=True)

        return run_op("dirichlet_rsample", fn, (self.concentration,))

    def log_prob(self, value):
        value = _t(value)

        def fn(v, a):
            return jnp.sum((a - 1) * jnp.log(v), -1) \
                + jsp.gammaln(jnp.sum(a, -1)) - jnp.sum(jsp.gammaln(a), -1)

        return run_op("dirichlet_log_prob", fn,
                      (value, self.concentration))


# ---------------------------------------------------------------------------
# discrete
# ---------------------------------------------------------------------------
class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if probs is not None:
            self.probs = _t(probs)
        else:
            self.probs = _t(logits).sigmoid()
        super().__init__(self.probs._data.shape)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(p):
            return jax.random.bernoulli(key, p, out_shape) \
                .astype(jnp.float32)

        return run_op("bernoulli_sample", fn, (self.probs,),
                      differentiable=False)

    def log_prob(self, value):
        value = _t(value)

        def fn(v, p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)

        return run_op("bernoulli_log_prob", fn, (value, self.probs))

    def entropy(self):
        def fn(p):
            eps = 1e-7
            p = jnp.clip(p, eps, 1 - eps)
            return -(p * jnp.log(p) + (1 - p) * jnp.log1p(-p))

        return run_op("bernoulli_entropy", fn, (self.probs,))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before first success)."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(self.probs._data.shape)

    @property
    def mean(self):
        return (1.0 - self.probs) / self.probs

    @property
    def variance(self):
        return (1.0 - self.probs) / (self.probs * self.probs)

    def sample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(p):
            return jax.random.geometric(key, p, out_shape) \
                .astype(jnp.float32) - 1.0

        return run_op("geometric_sample", fn, (self.probs,),
                      differentiable=False)

    def log_prob(self, value):
        value = _t(value)
        return run_op(
            "geometric_log_prob",
            lambda v, p: v * jnp.log1p(-p) + jnp.log(p),
            (value, self.probs))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate._data.shape)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(r):
            return jax.random.poisson(key, r, out_shape) \
                .astype(jnp.float32)

        return run_op("poisson_sample", fn, (self.rate,),
                      differentiable=False)

    def log_prob(self, value):
        value = _t(value)
        return run_op(
            "poisson_log_prob",
            lambda v, r: v * jnp.log(r) - r - jsp.gammaln(v + 1),
            (value, self.rate))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if (probs is None) == (logits is None):
            raise ValueError("pass exactly one of probs/logits")
        if logits is not None:
            self.logits = _t(logits)
        else:
            self.logits = _t(probs).log()
        shape = self.logits._data.shape
        super().__init__(shape[:-1])
        self._n = shape[-1]

    @property
    def probs(self):
        from ..nn import functional as F
        return F.softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(logits):
            return jax.random.categorical(key, logits, shape=out_shape) \
                .astype(jnp.int32)

        return run_op("categorical_sample", fn, (self.logits,),
                      differentiable=False)

    def log_prob(self, value):
        value = _t(value)

        def fn(v, logits):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]

        return run_op("categorical_log_prob", fn, (value, self.logits))

    def entropy(self):
        def fn(logits):
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

        return run_op("categorical_entropy", fn, (self.logits,))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        shape = self.probs._data.shape
        super().__init__(shape[:-1], shape[-1:])

    @property
    def mean(self):
        return self.probs * float(self.total_count)

    def sample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)
        n = self.total_count

        def fn(p):
            logits = jnp.log(p)
            draws = jax.random.categorical(
                key, logits, shape=(n,) + out_shape)
            onehot = jax.nn.one_hot(draws, p.shape[-1], dtype=jnp.float32)
            return jnp.sum(onehot, axis=0)

        return run_op("multinomial_sample", fn, (self.probs,),
                      differentiable=False)

    def log_prob(self, value):
        value = _t(value)

        def fn(v, p):
            return jsp.gammaln(jnp.sum(v, -1) + 1) \
                - jnp.sum(jsp.gammaln(v + 1), -1) \
                + jnp.sum(v * jnp.log(p), -1)

        return run_op("multinomial_log_prob", fn, (value, self.probs))


# ---------------------------------------------------------------------------
# transforms + transformed distribution
# ---------------------------------------------------------------------------
class Transform:
    """Bijector base (reference transform.py Transform)."""

    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(y))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _t(loc)
        self.scale = _t(scale)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return self.scale.abs().log()


class ExpTransform(Transform):
    def forward(self, x):
        return x.exp()

    def inverse(self, y):
        return y.log()

    def forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    def forward(self, x):
        return x.sigmoid()

    def inverse(self, y):
        return (y / (1.0 - y)).log()

    def forward_log_det_jacobian(self, x):
        s = x.sigmoid()
        return (s * (1.0 - s)).log()


class TanhTransform(Transform):
    def forward(self, x):
        return x.tanh()

    def inverse(self, y):
        return 0.5 * ((1.0 + y) / (1.0 - y)).log()

    def forward_log_det_jacobian(self, x):
        return (1.0 - x.tanh() * x.tanh()).log()


class TransformedDistribution(Distribution):
    """base pushed through a chain of transforms (reference
    transformed_distribution.py)."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x.detach()

    def log_prob(self, value):
        logp = None
        y = value
        for t in reversed(self.transforms):
            x = t.inverse(y)
            term = t.forward_log_det_jacobian(x)
            logp = term if logp is None else logp + term
            y = x
        base_lp = self.base.log_prob(y)
        return base_lp - logp if logp is not None else base_lp


# ---------------------------------------------------------------------------
# kl_divergence registry
# ---------------------------------------------------------------------------
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Reference kl.py register_kl decorator."""

    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        for (pc, qc), f in _KL_REGISTRY.items():
            if isinstance(p, pc) and isinstance(q, qc):
                fn = f
                break
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__}) "
            "is not registered")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1.0 - var_ratio.log())


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return ((q.high - q.low) / (p.high - p.low)).log()


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def fn(pp, qp):
        eps = 1e-7
        pp = jnp.clip(pp, eps, 1 - eps)
        qp = jnp.clip(qp, eps, 1 - eps)
        return pp * (jnp.log(pp) - jnp.log(qp)) \
            + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp))

    return run_op("kl_bernoulli", fn, (p.probs, q.probs))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    def fn(pl, ql):
        lp = jax.nn.log_softmax(pl, -1)
        lq = jax.nn.log_softmax(ql, -1)
        return jnp.sum(jnp.exp(lp) * (lp - lq), -1)

    return run_op("kl_categorical", fn, (p.logits, q.logits))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = p.rate / q.rate
    return r.log() + 1.0 / r - 1.0


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    def fn(pa, pr, qa, qr):
        return ((pa - qa) * jsp.digamma(pa) - jsp.gammaln(pa)
                + jsp.gammaln(qa) + qa * (jnp.log(pr) - jnp.log(qr))
                + pa * (qr / pr - 1.0))

    return run_op("kl_gamma", fn, (p.concentration, p.rate,
                                   q.concentration, q.rate))


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def fn(pa, pb, qa, qb):
        def lbeta(a, b):
            return jsp.gammaln(a) + jsp.gammaln(b) - jsp.gammaln(a + b)
        return (lbeta(qa, qb) - lbeta(pa, pb)
                + (pa - qa) * jsp.digamma(pa)
                + (pb - qb) * jsp.digamma(pb)
                + (qa - pa + qb - pb) * jsp.digamma(pa + pb))

    return run_op("kl_beta", fn, (p.alpha, p.beta, q.alpha, q.beta))


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    def fn(pl, ps, ql, qs):
        t = jnp.abs(pl - ql)
        return (jnp.log(qs) - jnp.log(ps)
                + (ps * jnp.exp(-t / ps) + t) / qs - 1.0)

    return run_op("kl_laplace", fn, (p.loc, p.scale, q.loc, q.scale))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def fn(pa, qa):
        sp = jnp.sum(pa, -1)
        return (jsp.gammaln(sp) - jnp.sum(jsp.gammaln(pa), -1)
                - jsp.gammaln(jnp.sum(qa, -1))
                + jnp.sum(jsp.gammaln(qa), -1)
                + jnp.sum((pa - qa) * (jsp.digamma(pa)
                                       - jsp.digamma(sp)[..., None]), -1))

    return run_op("kl_dirichlet", fn, (p.concentration, q.concentration))


class Binomial(Distribution):
    """Reference `distribution/binomial.py`."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(jnp.broadcast_shapes(
            self.total_count._data.shape, self.probs._data.shape))

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1 - self.probs)

    def sample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(n, p):
            return jax.random.binomial(key, n, p, shape=out_shape) \
                .astype(jnp.float32)

        return run_op("binomial_sample", fn, (self.total_count, self.probs),
                      differentiable=False)

    def log_prob(self, value):
        value = _t(value)

        def fn(v, n, p):
            logc = (jsp.gammaln(n + 1) - jsp.gammaln(v + 1)
                    - jsp.gammaln(n - v + 1))
            return logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p)

        return run_op("binomial_log_prob", fn,
                      (value, self.total_count, self.probs))

    def entropy(self):
        # half the support often suffices; exact via summation over k
        def fn(n, p):
            nmax = int(np.max(np.asarray(n)))
            k = jnp.arange(nmax + 1, dtype=jnp.float32)
            logc = (jsp.gammaln(n[..., None] + 1) - jsp.gammaln(k + 1)
                    - jsp.gammaln(n[..., None] - k + 1))
            logp = logc + k * jnp.log(p[..., None]) \
                + (n[..., None] - k) * jnp.log1p(-p[..., None])
            mask = k <= n[..., None]
            pk = jnp.where(mask, jnp.exp(logp), 0.0)
            return -jnp.sum(pk * jnp.where(mask, logp, 0.0), axis=-1)

        return run_op("binomial_entropy", fn,
                      (self.total_count, self.probs))


class ContinuousBernoulli(Distribution):
    """Reference `distribution/continuous_bernoulli.py`: the [0, 1]
    continuous relaxation with normalizer C(p)."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _t(probs)
        self._lims = lims
        super().__init__(self.probs._data.shape)

    def _log_norm(self, p):
        # C(p) = 2*atanh(1-2p) / (1-2p) for p != 0.5, else 2
        safe = jnp.clip(p, 1e-6, 1 - 1e-6)
        near_half = jnp.abs(safe - 0.5) < (self._lims[1] - 0.5)
        x = jnp.where(near_half, 0.4, safe)  # safe value for the formula
        c = 2 * jnp.arctanh(1 - 2 * x) / (1 - 2 * x)
        # 2nd-order Taylor around 0.5: C = 2 + (4/3)*(p-1/2)^2
        taylor = 2.0 + (4.0 / 3.0) * (safe - 0.5) ** 2
        return jnp.log(jnp.where(near_half, taylor, c))

    @property
    def mean(self):
        def fn(p):
            safe = jnp.clip(p, 1e-6, 1 - 1e-6)
            near_half = jnp.abs(safe - 0.5) < (self._lims[1] - 0.5)
            x = jnp.where(near_half, 0.4, safe)
            m = x / (2 * x - 1) + 1 / (2 * jnp.arctanh(1 - 2 * x))
            return jnp.where(near_half, 0.5, m)

        return run_op("cb_mean", fn, (self.probs,))

    def sample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape)

        def fn(p):
            u = jax.random.uniform(key, out_shape)
            safe = jnp.clip(p, 1e-6, 1 - 1e-6)
            near_half = jnp.abs(safe - 0.5) < (self._lims[1] - 0.5)
            x = jnp.where(near_half, 0.4, safe)
            # inverse CDF for p != 0.5
            icdf = (jnp.log1p(u * (2 * x - 1) / (1 - x))
                    / (jnp.log(x) - jnp.log1p(-x)))
            return jnp.where(near_half, u, icdf)

        return run_op("cb_sample", fn, (self.probs,),
                      differentiable=False)

    def log_prob(self, value):
        value = _t(value)

        def fn(v, p):
            safe = jnp.clip(p, 1e-6, 1 - 1e-6)
            return (v * jnp.log(safe) + (1 - v) * jnp.log1p(-safe)
                    + self._log_norm(safe))

        return run_op("cb_log_prob", fn, (value, self.probs))


class Independent(Distribution):
    """Reference `distribution/independent.py`: reinterpret the last
    ``reinterpreted_batch_rank`` batch dims as event dims (log_prob
    sums over them)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        if self.rank > len(base.batch_shape):
            raise ValueError(
                f"reinterpreted_batch_rank {self.rank} exceeds base batch "
                f"rank {len(base.batch_shape)}")
        super().__init__(tuple(base.batch_shape)[:len(base.batch_shape)
                                                 - self.rank])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        for _ in range(self.rank):
            lp = lp.sum(-1)
        return lp

    def entropy(self):
        e = self.base.entropy()
        for _ in range(self.rank):
            e = e.sum(-1)
        return e


class MultivariateNormal(Distribution):
    """Reference `distribution/multivariate_normal.py` (loc +
    covariance_matrix parameterization)."""

    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        self.loc = _t(loc)
        if (covariance_matrix is None) == (scale_tril is None):
            raise ValueError(
                "pass exactly one of covariance_matrix/scale_tril")
        if covariance_matrix is not None:
            self.covariance_matrix = _t(covariance_matrix)
            self._tril = run_op(
                "mvn_chol", lambda c: jnp.linalg.cholesky(c),
                (self.covariance_matrix,))
        else:
            self._tril = _t(scale_tril)
            self.covariance_matrix = run_op(
                "mvn_cov", lambda L: L @ jnp.swapaxes(L, -1, -2),
                (self._tril,))
        super().__init__(self.loc._data.shape[:-1])
        self._d = self.loc._data.shape[-1]

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return run_op(
            "mvn_var", lambda c: jnp.diagonal(c, axis1=-2, axis2=-1),
            (self.covariance_matrix,))

    def sample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape) + (self._d,)

        def fn(mu, L):
            eps = jax.random.normal(key, out_shape)
            return mu + jnp.einsum("...ij,...j->...i", L, eps)

        return run_op("mvn_sample", fn, (self.loc, self._tril),
                      differentiable=False)

    def rsample(self, shape=()):
        key = frandom.next_key()
        out_shape = _shape(shape, self.batch_shape) + (self._d,)

        def fn(mu, L):
            eps = jax.random.normal(key, out_shape)
            return mu + jnp.einsum("...ij,...j->...i", L, eps)

        return run_op("mvn_rsample", fn, (self.loc, self._tril))

    def log_prob(self, value):
        value = _t(value)

        def fn(v, mu, L):
            diff = v - mu
            sol = jax.scipy.linalg.solve_triangular(
                L, diff[..., None], lower=True)[..., 0]
            maha = jnp.sum(sol ** 2, axis=-1)
            logdet = 2 * jnp.sum(
                jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)
            return -0.5 * (self._d * jnp.log(2 * jnp.pi) + logdet + maha)

        return run_op("mvn_log_prob", fn, (value, self.loc, self._tril))

    def entropy(self):
        def fn(L):
            logdet = 2 * jnp.sum(
                jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)
            return 0.5 * self._d * (1 + jnp.log(2 * jnp.pi)) + 0.5 * logdet

        return run_op("mvn_entropy", fn, (self._tril,))


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    def fn(mu_p, Lp, mu_q, Lq):
        d = mu_p.shape[-1]
        diff = mu_q - mu_p
        sol_mean = jax.scipy.linalg.solve_triangular(
            Lq, diff[..., None], lower=True)[..., 0]
        m = jax.scipy.linalg.solve_triangular(
            Lq, Lp, lower=True)
        tr = jnp.sum(m ** 2, axis=(-2, -1))
        logdet_p = 2 * jnp.sum(
            jnp.log(jnp.diagonal(Lp, axis1=-2, axis2=-1)), axis=-1)
        logdet_q = 2 * jnp.sum(
            jnp.log(jnp.diagonal(Lq, axis1=-2, axis2=-1)), axis=-1)
        return 0.5 * (tr + jnp.sum(sol_mean ** 2, axis=-1) - d
                      + logdet_q - logdet_p)

    return run_op("kl_mvn", fn, (p.loc, p._tril, q.loc, q._tril))


__all__ += ["Binomial", "ContinuousBernoulli", "Independent",
            "MultivariateNormal"]
