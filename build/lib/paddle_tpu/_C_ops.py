"""``paddle_tpu._C_ops`` — the generated op-dispatch surface.

Reference: `python/paddle/_C_ops.py:20` re-exports the pybind functions
generated from `phi/api/yaml/ops.yaml`. Here the namespace is generated
at first access from the same single source (`ops/schema/ops.yaml`):
only ops listed in the schema are reachable, and each resolves to the
``@defop``-registered autograd-aware wrapper. User code written against
paddle's private ``_C_ops`` API ports over unchanged.
"""

from __future__ import annotations

import difflib

_table = None


def _build():
    global _table
    if _table is not None:
        return _table
    from .ops.schema import load_schema, _import_op_surface
    from .tensor.registry import OPS

    _import_op_surface()   # lazy subpackages (vision/text/...) hold ops too
    _table = {}
    for name in load_schema():
        info = OPS.get(name)
        if info is not None:
            _table[name] = info["wrapper"]
    return _table


def __getattr__(name):
    table = _build()
    try:
        return table[name]
    except KeyError:
        near = difflib.get_close_matches(name, table, n=3)
        hint = f" (did you mean {', '.join(near)}?)" if near else ""
        raise AttributeError(
            f"_C_ops has no op '{name}'{hint} — ops are generated from "
            "paddle_tpu/ops/schema/ops.yaml") from None


def __dir__():
    return sorted(_build())
