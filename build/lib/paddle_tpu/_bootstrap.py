"""Multi-host coordination-service bootstrap (single implementation).

Called from ``paddle_tpu/__init__.py`` at import time (worker processes
spawned by the launch CLI, marked by ``PADDLE_LOCAL_RANK``) and from
``distributed.env.init_parallel_env`` (manual bootstrap before any jax
call). Reference analog: `python/paddle/distributed/parallel.py:943`.
"""

from __future__ import annotations

import os

_done = False


def bootstrap_distributed():
    """jax.distributed.initialize from the PADDLE_* env. Returns True if
    the coordination service was joined (idempotent)."""
    global _done
    if _done:
        return True
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    master = os.environ.get("PADDLE_MASTER") \
        or os.environ.get("PADDLE_CURRENT_ENDPOINT")
    if n <= 1 or not master:
        return False
    import jax
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        # CPU multi-process (the test/simulation path) needs an explicit
        # cross-process collectives backend; TPU uses the ICI/DCN runtime
        jax.config.update(
            "jax_cpu_collectives_implementation",
            os.environ.get("PADDLE_CPU_COLLECTIVES", "gloo"))
    jax.distributed.initialize(
        coordinator_address=master,
        num_processes=n,
        process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    _done = True
    return True
