"""ASP — automatic n:m structured sparsity (reference:
`python/paddle/incubate/asp/asp.py`, `utils.py`).

Workflow identical to the reference: ``prune_model`` computes n:m masks
over supported weights (largest-|w| n of every m consecutive elements
along the contraction dim) and applies them; ``decorate`` wraps an
optimizer so the masks are re-applied after every step, keeping pruned
positions at zero through sparse training. TPU note: XLA has no 2:4
sparse tensor-core path — the masks' value here is model-compression
semantics (and forward-compatibility with sparsity-aware hardware), so
the implementation is pure mask bookkeeping over ordinary dense ops.
"""

from __future__ import annotations

import numpy as np

from ... import nn
from ...framework.tensor import Tensor

__all__ = ["calculate_density", "decorate", "prune_model",
           "set_excluded_layers", "reset_excluded_layers",
           "add_supported_layer", "get_mask_1d", "check_mask_1d"]

# param name -> numpy mask; populated by prune_model, consumed by decorate
_masks: dict[int, tuple] = {}
_excluded_param_names: set[str] = set()
_supported_types = {nn.Linear}


def calculate_density(x):
    """Fraction of non-zero entries (reference `asp.py:calculate_density`)."""
    arr = np.asarray(getattr(x, "_data", x))
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def get_mask_1d(mat, n, m):
    """Keep the ``n`` largest-|.| of every ``m`` consecutive elements of
    each row (reference `utils.py:get_mask_1d`). mat: 2-D numpy."""
    mat = np.asarray(mat)
    rows, cols = mat.shape
    if cols % m:
        raise ValueError(f"columns ({cols}) not divisible by m={m}")
    groups = np.abs(mat).reshape(rows, cols // m, m)
    order = np.argsort(groups, axis=-1)          # ascending
    mask = np.zeros_like(groups, dtype=bool)
    np.put_along_axis(mask, order[..., m - n:], True, axis=-1)
    return mask.reshape(rows, cols).astype(mat.dtype)


def check_mask_1d(mat, n, m):
    """True iff every m-chunk of every row has at most n non-zeros."""
    mat = np.asarray(mat)
    rows, cols = mat.shape
    if cols % m:
        return False
    chunks = mat.reshape(rows, cols // m, m)
    return bool((np.count_nonzero(chunks, axis=-1) <= n).all())


def set_excluded_layers(param_names, main_program=None):
    _excluded_param_names.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded_param_names.clear()


def add_supported_layer(layer_type):
    """Register an additional nn.Layer subclass whose ``weight`` should
    be pruned (reference `supported_layer_list.py`)."""
    _supported_types.add(layer_type)


def _iter_prunable(model):
    for name, sub in model.named_sublayers(include_self=True):
        if type(sub) in _supported_types \
                and getattr(sub, "weight", None) is not None:
            w = sub.weight
            pname = w.name or f"{name}.weight"
            if pname not in _excluded_param_names:
                yield pname, sub, w


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute + apply n:m masks over the model's supported weights.
    Returns {param_name: density_after} (reference returns the masks via
    the internal ASPHelper; the density map is more useful here)."""
    if mask_algo not in ("mask_1d",):
        raise ValueError(
            f"mask_algo {mask_algo!r} not supported (mask_1d only: 2-D "
            "permutation search has no TPU payoff)")
    out = {}
    for pname, _layer, w in _iter_prunable(model):
        arr = np.asarray(w._data)
        if arr.ndim != 2 or arr.shape[0] % m:
            continue
        # Linear weight is [in, out]; y = x @ W contracts over rows, so
        # the n:m pattern runs down each column -> mask the transpose
        mask = get_mask_1d(arr.T, n, m).T
        w.set_value((arr * mask).astype(arr.dtype))
        if with_mask:
            _masks[pname] = (w, mask)
        out[pname] = calculate_density(arr * mask)
    return out


def decorate(optimizer):
    """Wrap ``optimizer.step`` to re-apply the pruning masks after each
    update (reference `asp.py:decorate` / OptimizerWithSparsityGuarantee)."""
    inner_step = optimizer.step

    def masked_step(*args, **kwargs):
        result = inner_step(*args, **kwargs)
        for w, mask in _masks.values():
            w.set_value(np.asarray(w._data) * mask)
        return result

    optimizer.step = masked_step
    return optimizer


def _reset_state():
    """Test hook: forget masks + exclusions."""
    _masks.clear()
    _excluded_param_names.clear()
