"""``paddle_tpu.incubate`` — incubating APIs (fused transformer ops, MoE).

Reference surface: `python/paddle/incubate/` (fused functional ops in
`incubate/nn/functional/`, MoE under `incubate/distributed/models/moe/`).
"""

from . import nn  # noqa: F401
from . import moe  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from . import autograd  # noqa: F401

__all__ = ["nn", "moe"]

from ..geometric import (  # noqa: F401  (reference incubate.segment_*)
    segment_sum, segment_mean, segment_max, segment_min)
