"""Functional autograd API (reference:
`python/paddle/incubate/autograd/` — jvp/vjp/Jacobian/Hessian + the
prim flags).

TPU-native: these are direct surfaces over ``jax.jvp``/``jax.vjp`` on
the pure function extracted from the Tensor computation — forward-mode
AD is native here (the reference lowers to primitive ops to get it).
``enable_prim`` is therefore a no-op that reports True: everything is
always traced to primitives (StableHLO) by construction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "prim_enabled"]


def _unwrap(xs):
    if isinstance(xs, (list, tuple)):
        return type(xs)(_unwrap(x) for x in xs)
    return xs._data if isinstance(xs, Tensor) else jnp.asarray(xs)


def _wrap(xs):
    if isinstance(xs, (list, tuple)):
        return type(xs)(_wrap(x) for x in xs)
    return Tensor(xs)


def _as_pure(func):
    def pure(*arrays):
        out = func(*[Tensor(a) for a in arrays])
        if isinstance(out, (list, tuple)):
            return tuple(o._data for o in out)
        return out._data
    return pure


def jvp(func, xs, v=None):
    """Forward-mode: returns (func(xs), J @ v) (reference
    `incubate/autograd/functional.py:jvp`)."""
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [_unwrap(x) for x in xs]
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        v = v if isinstance(v, (list, tuple)) else [v]
        tangents = [_unwrap(t) for t in v]
    out, tangent_out = jax.jvp(_as_pure(func), tuple(arrays),
                               tuple(tangents))
    return _wrap(out), _wrap(tangent_out)


def vjp(func, xs, v=None):
    """Reverse-mode: returns (func(xs), v^T @ J) (reference
    `functional.py:vjp`)."""
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [_unwrap(x) for x in xs]
    out, vjp_fn = jax.vjp(_as_pure(func), *arrays)
    if v is None:
        cotangents = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v = v if isinstance(v, (list, tuple)) else [v]
        cotangents = tuple(_unwrap(t) for t in v)
        if not isinstance(out, tuple):
            cotangents = cotangents[0]
    grads = vjp_fn(cotangents)
    grads = grads[0] if len(grads) == 1 else list(grads)
    return _wrap(out), _wrap(grads)


class Jacobian:
    """Lazy full Jacobian (reference `functional.py:Jacobian`): index or
    materialize via ``[:]``; rows computed with jax.jacfwd."""

    def __init__(self, func, xs, is_batched=False):
        xs = xs if isinstance(xs, (list, tuple)) else [xs]
        if len(xs) != 1:
            raise NotImplementedError(
                "Jacobian over multiple inputs: pass one stacked tensor")
        self._x = _unwrap(xs[0])
        self._mat = None
        self._func = func
        self._batched = is_batched

    def _materialize(self):
        if self._mat is None:
            jac = jax.jacfwd(_as_pure(self._func))(self._x)
            if self._batched:
                # [B, out..., B, in...] -> diagonal over the batch
                b = self._x.shape[0]
                jac = jnp.stack([jac[i, ..., i, :] for i in range(b)])
            else:
                jac = jac.reshape(-1, int(jnp.size(self._x)))
            self._mat = jac
        return self._mat

    def __getitem__(self, idx):
        return Tensor(self._materialize()[idx])

    @property
    def shape(self):
        return list(self._materialize().shape)


class Hessian:
    """Lazy Hessian of a scalar function (reference
    `functional.py:Hessian`)."""

    def __init__(self, func, xs, is_batched=False):
        if is_batched:
            raise NotImplementedError("batched Hessian not supported")
        xs = xs if isinstance(xs, (list, tuple)) else [xs]
        self._x = _unwrap(xs[0])
        self._func = func
        self._mat = None

    def _materialize(self):
        if self._mat is None:
            def scalar(x):
                out = _as_pure(self._func)(x)
                return jnp.reshape(out, ())
            h = jax.hessian(scalar)(self._x)
            n = int(jnp.size(self._x))
            self._mat = h.reshape(n, n)
        return self._mat

    def __getitem__(self, idx):
        return Tensor(self._materialize()[idx])

    @property
    def shape(self):
        return list(self._materialize().shape)


def enable_prim():
    """No-op: this framework always traces to primitives (StableHLO)."""


def disable_prim():
    """No-op (see enable_prim)."""


def prim_enabled():
    return True
