"""Kernel/layout/dataloader auto-tuning config (reference:
`python/paddle/incubate/autotune.py:set_config` over
`phi/kernels/autotune/`).

TPU mapping of the three knobs:
- kernel: XLA's own autotuner owns GEMM/conv algorithm choice; the knob
  here selects the Pallas-vs-XLA attention path empirically — when
  enabled, the first ``flash_attention``-eligible call of each shape
  times both paths and caches the winner (the reference's exhaustive-
  search-then-cache semantics at our kernel boundary).
- layout: a no-op acknowledged in the returned status — XLA chooses
  layouts during compilation; there is no NCHW/NHWC choice to make.
- dataloader: :func:`tune_num_workers` times a DataLoader over candidate
  worker counts and returns the fastest (call it when the domain is
  enabled; automatic in ``hapi.Model.fit`` is not wired — explicit
  beats implicit for a tuning probe that consumes real batches).
"""

from __future__ import annotations

import json

__all__ = ["set_config", "get_config", "kernel_choice",
           "tune_num_workers"]

_config = {
    "kernel": {"enable": False, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False},
}
_kernel_cache: dict = {}


def set_config(config=None):
    """Enable/disable auto-tuning domains (dict, json path, or None for
    all-on, matching the reference)."""
    if config is None:
        for v in _config.values():
            v["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for key, val in config.items():
        if key not in _config:
            raise ValueError(
                f"unknown autotune domain {key!r}; expected one of "
                f"{sorted(_config)}")
        _config[key].update(val)


def get_config():
    return {k: dict(v) for k, v in _config.items()}


def kernel_choice(key, candidates, args):
    """Time ``candidates`` ({name: fn}) once for ``key`` and cache the
    winner; subsequent calls dispatch directly. Used by the attention
    dispatch seam when kernel tuning is enabled."""
    import time

    import jax

    if not _config["kernel"]["enable"]:
        raise RuntimeError("kernel autotuning is disabled")
    chosen = _kernel_cache.get(key)
    if chosen is None:
        timings = {}
        for name, fn in candidates.items():
            out = fn(*args)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            timings[name] = time.perf_counter() - t0
        chosen = min(timings, key=timings.get)
        _kernel_cache[key] = chosen
    return chosen, candidates[chosen]


def tune_num_workers(dataset, batch_size, candidates=(0, 2, 4),
                     probe_batches=8, **loader_kwargs):
    """Time ``probe_batches`` batches per candidate worker count and
    return the fastest (the reference dataloader-tuning knob)."""
    import itertools
    import time

    from ..io import DataLoader

    if not _config["dataloader"]["enable"]:
        raise RuntimeError("dataloader autotuning is disabled")
    timings = {}
    for n in candidates:
        loader = DataLoader(dataset, batch_size=batch_size, num_workers=n,
                            **loader_kwargs)
        it = iter(loader)
        next(it)  # spin-up cost excluded
        t0 = time.perf_counter()
        for _ in itertools.islice(it, probe_batches):
            pass
        timings[n] = time.perf_counter() - t0
    return min(timings, key=timings.get)
