"""Fused transformer functionals.

Reference capability: `python/paddle/incubate/nn/functional/` — `swiglu.py`,
`fused_rms_norm.py`, `fused_layer_norm.py`, `fused_rotary_position_embedding`
(CUDA kernels under `paddle/phi/kernels/fusion/gpu/`). TPU-native design:
each "fused" op here is a single pure jax function executed through the
autograd tape (`run_op`), so under ``jit``/``to_static`` XLA fuses the whole
chain into one kernel on the VPU/MXU — the fusion the reference hand-writes
in CUDA falls out of the compiler. Normalizations accumulate in fp32
(TPU numerics idiom) and cast back to the input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ....framework.tensor import run_op
from ....framework import random as frandom
from ....tensor.registry import OPS

# raw jnp-level normalization cores (single source of the norm math,
# shared with nn.functional.layer_norm / rms_norm)
_rms_core = None
_ln_core = None


def _norm_cores():
    global _rms_core, _ln_core
    if _rms_core is None:
        from ....nn import functional as _  # ensure norm ops registered
        _rms_core = OPS["rms_norm"]["fn"]
        _ln_core = OPS["layer_norm"]["fn"]
    return _rms_core, _ln_core

__all__ = [
    "swiglu",
    "fused_rms_norm",
    "fused_layer_norm",
    "fused_rotary_position_embedding",
    "fused_dropout_add",
    "fused_linear",
    "fused_bias_act",
]


def swiglu(x, y=None, name=None):
    """SwiGLU: ``silu(x) * y``; with ``y=None``, ``x`` is split in half on
    the last axis (reference: `incubate/nn/functional/swiglu.py`)."""
    if y is None:
        def fn(x_):
            a, b = jnp.split(x_, 2, axis=-1)
            return jax.nn.silu(a) * b
        return run_op("swiglu", fn, (x,))

    def fn(x_, y_):
        return jax.nn.silu(x_) * y_
    return run_op("swiglu", fn, (x, y))


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None, name=None):
    """RMSNorm with optional pre-norm residual add (reference:
    `incubate/nn/functional/fused_rms_norm.py`).

    Computes ``out = rms_norm(x + bias + residual)``; returns ``out`` or
    ``(out, residual_out)`` when ``residual`` is given (residual_out is the
    pre-norm sum, fed to the next block's residual stream).
    """
    axes = begin_norm_axis
    rms_core, _ = _norm_cores()

    def fn(x_, w_, b_, bias_, res_):
        h = x_
        if bias_ is not None:
            h = h + bias_
        if res_ is not None:
            h = h + res_
        red = -1 if axes in (-1, h.ndim - 1) else tuple(range(axes, h.ndim))
        out = rms_core(h, weight=w_, epsilon=epsilon, bias=b_, axis=red)
        if res_ is not None:
            return out, h.astype(x_.dtype)
        return out

    return run_op("fused_rms_norm", fn, (x, norm_weight, norm_bias, bias,
                                         residual))


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, name=None):
    """LayerNorm with optional pre-norm residual add (reference:
    `incubate/nn/functional/fused_layer_norm.py`). Same return convention
    as :func:`fused_rms_norm`."""
    axes = begin_norm_axis
    _, ln_core = _norm_cores()

    def fn(x_, w_, b_, bias_, res_):
        h = x_
        if bias_ is not None:
            h = h + bias_
        if res_ is not None:
            h = h + res_
        start = axes if axes != -1 else h.ndim - 1
        normalized_shape = list(h.shape[start:])
        out = ln_core(h, normalized_shape, weight=w_, bias=b_,
                      epsilon=epsilon)
        if res_ is not None:
            return out, h.astype(x_.dtype)
        return out

    return run_op("fused_layer_norm", fn, (x, norm_weight, norm_bias, bias,
                                           residual))


def _default_sin_cos(seq_len, head_dim, base=10000.0):
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                          dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)                     # [S, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)     # [S, D]
    return jnp.sin(emb), jnp.cos(emb)


def _rotate_half(x):
    a, b = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-b, a], axis=-1)


def _apply_rope(x, sin_e, cos_e, neox):
    """x: [B, S, H, D]; sin_e/cos_e already expanded to a shape
    broadcastable against it ([*, S, 1, D], fp32). Rotation runs in fp32
    and casts back, so bf16 activations stay bf16."""
    xf = x.astype(jnp.float32)
    if neox:
        out = xf * cos_e + _rotate_half(xf) * sin_e
    else:
        # GPT-J interleaved style: pairs (x0,x1),(x2,x3),...
        half = sin_e.shape[-1] // 2
        s_, c_ = sin_e[..., :half], cos_e[..., :half]
        x1, x2 = xf[..., 0::2], xf[..., 1::2]
        out = jnp.stack([x1 * c_ - x2 * s_, x2 * c_ + x1 * s_],
                        axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0,
                                    name=None):
    """Rotary position embedding applied to q/k (v passes through untouched,
    matching the reference's tuple return). Inputs [B, S, H, D].

    Reference: `python/paddle/incubate/nn/functional/
    fused_rotary_position_embedding.py` (CUDA kernel
    `phi/kernels/fusion/gpu/fused_rope_kernel.cu`). On TPU the rotation is
    an elementwise chain XLA fuses into the surrounding matmuls.
    """
    if time_major:
        raise NotImplementedError(
            "fused_rotary_position_embedding: time_major=True is not "
            "supported; pass batch-major [B, S, H, D] inputs")
    neox = bool(use_neox_rotary_style)
    base = float(rotary_emb_base)

    def fn(q_, k_, v_, sin_, cos_, pos_):
        seq_len, head_dim = q_.shape[1], q_.shape[3]
        if pos_ is not None and (sin_ is None or cos_ is None):
            # compute angles directly from the positions — no table, so
            # arbitrary position ids (KV-cache decode) never clamp
            inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2,
                                             dtype=jnp.float32) / head_dim))
            ang = pos_.astype(jnp.float32)[..., None] * inv   # [B, S, D/2]
            emb = jnp.concatenate([ang, ang], axis=-1)        # [B, S, D]
            sin_b, cos_b = jnp.sin(emb), jnp.cos(emb)
        elif pos_ is not None:
            sin_ = jnp.reshape(sin_, (-1, sin_.shape[-1]))  # accept [1,S,1,D]
            cos_ = jnp.reshape(cos_, (-1, cos_.shape[-1]))
            # per-batch gather from the user-provided table: [B, S, D]
            sin_b = jnp.take(sin_, pos_, axis=0)
            cos_b = jnp.take(cos_, pos_, axis=0)
        if pos_ is not None:
            sin_e = sin_b.astype(jnp.float32)[:, :, None, :]   # [B, S, 1, D]
            cos_e = cos_b.astype(jnp.float32)[:, :, None, :]

            def app(x):
                return _apply_rope(x, sin_e, cos_e, neox)
        else:
            if sin_ is None or cos_ is None:
                sin_, cos_ = _default_sin_cos(seq_len, head_dim, base)
            sin_ = jnp.reshape(sin_, (-1, sin_.shape[-1]))  # accept [1,S,1,D]
            cos_ = jnp.reshape(cos_, (-1, cos_.shape[-1]))
            sin_e = sin_[:seq_len].astype(jnp.float32)[None, :, None, :]
            cos_e = cos_[:seq_len].astype(jnp.float32)[None, :, None, :]

            def app(x):
                return _apply_rope(x, sin_e, cos_e, neox)

        outs = [app(q_)]
        if k_ is not None:
            outs.append(app(k_))
        if v_ is not None:
            outs.append(v_)  # untouched
        return tuple(outs) if len(outs) > 1 else outs[0]

    out = run_op("fused_rotary_position_embedding", fn,
                 (q, k, v, sin, cos, position_ids))
    outs = list(out) if isinstance(out, tuple) else [out]
    result = [outs.pop(0)]
    result.append(outs.pop(0) if k is not None else None)
    result.append(outs.pop(0) if v is not None else None)
    return tuple(result)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """``dropout(x) + y`` in one fused region (reference:
    `incubate/nn/functional/fused_dropout_add.py`)."""
    if not training or p == 0.0:
        def fn(x_, y_):
            if mode == "downscale_in_infer" and not training:
                return x_ * (1.0 - p) + y_
            return x_ + y_
        return run_op("fused_dropout_add", fn, (x, y))
    key = frandom.next_key()

    def fn(x_, y_, k_):
        keep = jax.random.bernoulli(k_, 1.0 - p, x_.shape)
        if mode == "upscale_in_train":
            d = jnp.where(keep, x_ / (1.0 - p), 0.0)
        else:
            d = jnp.where(keep, x_, 0.0)
        return d.astype(x_.dtype) + y_

    return run_op("fused_dropout_add", fn, (x, y, key))


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """matmul + bias epilogue (reference:
    `incubate/nn/functional/fused_matmul_bias.py`, cublasLt epilogue —
    on TPU XLA fuses the bias add into the MXU matmul)."""
    def fn(x_, w_, b_):
        w_ = w_.T if transpose_weight else w_
        out = jnp.matmul(x_, w_)
        if b_ is not None:
            out = out + b_
        return out
    return run_op("fused_linear", fn, (x, weight, bias))


_ACTS = {
    "gelu": jax.nn.gelu,
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "identity": lambda x: x,
    "swiglu": None,  # handled specially
    "geglu": None,
}


def fused_bias_act(x, bias=None, act_method="gelu", name=None, **kwargs):
    """bias + activation epilogue (reference:
    `phi/kernels/fusion/gpu/fused_bias_act_kernel.cu`). ``swiglu``/``geglu``
    split the last axis in half (gated variants)."""
    act = act_method.lower()
    if act not in _ACTS:
        raise ValueError(f"unsupported act_method {act_method!r}")

    def fn(x_, b_):
        h = x_ + b_ if b_ is not None else x_
        if act in ("swiglu", "geglu"):
            a, g = jnp.split(h, 2, axis=-1)
            gate = jax.nn.silu(a) if act == "swiglu" else jax.nn.gelu(a)
            return gate * g
        return _ACTS[act](h)

    return run_op("fused_bias_act", fn, (x, bias))
