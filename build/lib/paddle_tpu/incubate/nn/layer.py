"""Fused transformer layer classes (reference:
`python/paddle/incubate/nn/layer/fused_transformer.py`,
`fused_linear.py`, `fused_dropout_add.py`).

On TPU the "fusion" is XLA's job — these classes provide the reference's
layer API over the in-tree fused functionals
(`paddle_tpu/incubate/nn/functional`) and the Pallas attention dispatch,
so models written against the incubate fused layers port unchanged while
the compiler decides the actual kernel grouping.
"""

from __future__ import annotations

import math

from ... import nn
from ...nn import functional as F
from ...nn.initializer import Constant
from . import functional as FI

__all__ = ["FusedLinear", "FusedDropoutAdd",
           "FusedBiasDropoutResidualLayerNorm", "FusedMultiHeadAttention",
           "FusedFeedForward", "FusedTransformerEncoderLayer"]


class FusedLinear(nn.Layer):
    """Reference `fused_linear.py:19` (cublasLt epilogue fusion there;
    XLA fuses bias+gelu into the matmul here)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = [out_features, in_features] if transpose_weight \
            else [in_features, out_features]
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        w = self.weight.t() if self.transpose_weight else self.weight
        return FI.fused_linear(x, w, self.bias)


class FusedDropoutAdd(nn.Layer):
    """Reference `fused_dropout_add.py`: out = residual + dropout(x)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        return FI.fused_dropout_add(x, y, p=self.p, training=self.training,
                                    mode=self.mode)


class FusedBiasDropoutResidualLayerNorm(nn.Layer):
    """Reference `fused_transformer.py:83`:
    ``layer_norm(residual + dropout(x + bias))``."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter([embed_dim],
                                                 attr=bias_attr,
                                                 is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        h = FI.fused_dropout_add(x + self.linear_bias, residual,
                                 p=self.dropout_rate,
                                 training=self.training)
        return F.layer_norm(h, [self.embed_dim], weight=self.ln_scale,
                            bias=self.ln_bias, epsilon=self.epsilon)


class FusedMultiHeadAttention(nn.Layer):
    """Reference `fused_transformer.py:189`: pre/post-LN self-attention
    block with fused qkv and the flash-attention dispatch."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        if need_weights:
            raise NotImplementedError(
                "need_weights=True: the fused path never materializes "
                "attention probabilities")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        # fused qkv: one [D, 3D] matmul (the fusion the reference's
        # kernel does; one MXU call here)
        self.qkv_weight = self.create_parameter(
            [embed_dim, 3 * embed_dim], attr=qkv_weight_attr)
        self.qkv_bias = None if qkv_bias_attr is False else \
            self.create_parameter([3 * embed_dim], attr=qkv_bias_attr,
                                  is_bias=True)
        self.linear_weight = self.create_parameter(
            [embed_dim, embed_dim], attr=linear_weight_attr)
        self.linear_bias = None if linear_bias_attr is False else \
            self.create_parameter([embed_dim], attr=linear_bias_attr,
                                  is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [embed_dim], attr=pre_ln_scale_attr,
            default_initializer=Constant(1.0))
        self.pre_ln_bias = self.create_parameter([embed_dim], is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=ln_scale_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if cache is not None:
            raise NotImplementedError(
                "cache: use LlamaForCausalLM-style static caches or the "
                "paged serving engine for decode")
        residual = query
        x = query
        if self.normalize_before:
            x = F.layer_norm(x, [self.embed_dim], weight=self.pre_ln_scale,
                             bias=self.pre_ln_bias, epsilon=self.epsilon)
        qkv = FI.fused_linear(x, self.qkv_weight, self.qkv_bias)
        b, s = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, training=self.training)
        out = out.reshape([b, s, self.embed_dim])
        out = FI.fused_linear(out, self.linear_weight, self.linear_bias)
        out = FI.fused_dropout_add(out, residual, p=self.dropout_rate,
                                   training=self.training)
        if not self.normalize_before:
            out = F.layer_norm(out, [self.embed_dim], weight=self.ln_scale,
                               bias=self.ln_bias, epsilon=self.epsilon)
        return out


class FusedFeedForward(nn.Layer):
    """Reference `fused_transformer.py:483`: pre/post-LN MLP block."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.d_model = d_model
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = dropout_rate if act_dropout_rate is None \
            else act_dropout_rate
        self.activation = activation
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=linear1_weight_attr)
        self.linear1_bias = self.create_parameter([dim_feedforward],
                                                  attr=linear1_bias_attr,
                                                  is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=linear2_weight_attr)
        self.linear2_bias = self.create_parameter([d_model],
                                                  attr=linear2_bias_attr,
                                                  is_bias=True)
        self.ln_scale = self.create_parameter(
            [d_model], attr=ln1_scale_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        residual = src
        x = src
        if self.normalize_before:
            x = F.layer_norm(x, [self.d_model], weight=self.ln_scale,
                             bias=self.ln_bias, epsilon=self.epsilon)
        x = FI.fused_bias_act(x @ self.linear1_weight, self.linear1_bias,
                              act_method=self.activation)
        x = F.dropout(x, p=self.act_dropout_rate, training=self.training)
        x = FI.fused_linear(x, self.linear2_weight, self.linear2_bias)
        x = FI.fused_dropout_add(x, residual, p=self.dropout_rate,
                                 training=self.training)
        if not self.normalize_before:
            x = F.layer_norm(x, [self.d_model], weight=self.ln_scale,
                             bias=self.ln_bias, epsilon=self.epsilon)
        return x


class FusedTransformerEncoderLayer(nn.Layer):
    """Reference `fused_transformer.py:697`: attention + FFN blocks."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout_rate = dropout_rate if attn_dropout_rate is None \
            else attn_dropout_rate
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
