from . import functional  # noqa: F401
from .layer import (  # noqa: F401
    FusedLinear, FusedDropoutAdd, FusedBiasDropoutResidualLayerNorm,
    FusedMultiHeadAttention, FusedFeedForward,
    FusedTransformerEncoderLayer,
)

__all__ = ["functional", "FusedLinear", "FusedDropoutAdd",
           "FusedBiasDropoutResidualLayerNorm", "FusedMultiHeadAttention",
           "FusedFeedForward", "FusedTransformerEncoderLayer"]
