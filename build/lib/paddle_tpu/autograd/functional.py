"""Functional differentiation API (reference: `python/paddle/autograd/autograd.py`
— jacobian/hessian). Implemented directly on JAX transforms, the idiomatic
TPU path (forward-over-reverse for hessians etc.)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor

__all__ = ["jacobian", "hessian", "jvp", "vjp"]


def _unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _wrap(x):
    if isinstance(x, jax.Array):
        return Tensor(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap(v) for v in x)
    return x


def _functionalize(func):
    def fn(*arrays):
        tensors = [Tensor(a, stop_gradient=False) for a in arrays]
        out = func(*tensors)
        return _unwrap(out)
    return fn


def jacobian(ys_func, xs, batch_axis=None):
    """``paddle.autograd.jacobian`` — here ``ys_func`` may be a callable over
    Tensors, or a Tensor already computed (in which case the tape is used)."""
    if callable(ys_func):
        arrays = _unwrap(xs) if isinstance(xs, (list, tuple)) else (_unwrap(xs),)
        jac = jax.jacrev(_functionalize(ys_func), argnums=tuple(range(len(arrays))))(*arrays)
        return _wrap(jac if len(arrays) > 1 else jac[0])
    raise TypeError("jacobian expects a callable as first argument")


def hessian(func, xs, batch_axis=None):
    arrays = _unwrap(xs) if isinstance(xs, (list, tuple)) else (_unwrap(xs),)
    hess = jax.hessian(_functionalize(func), argnums=tuple(range(len(arrays))))(*arrays)
    return _wrap(hess if len(arrays) > 1 else hess[0][0] if isinstance(hess[0], tuple) else hess[0])


def jvp(func, xs, v=None):
    arrays = tuple(_unwrap(xs)) if isinstance(xs, (list, tuple)) else (_unwrap(xs),)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        tangents = tuple(_unwrap(v)) if isinstance(v, (list, tuple)) else (_unwrap(v),)
    out, tangent_out = jax.jvp(_functionalize(func), arrays, tangents)
    return _wrap(out), _wrap(tangent_out)


def vjp(func, xs, v=None):
    arrays = tuple(_unwrap(xs)) if isinstance(xs, (list, tuple)) else (_unwrap(xs),)
    out, vjp_fn = jax.vjp(_functionalize(func), *arrays)
    if v is None:
        cot = jnp.ones_like(out) if not isinstance(out, (tuple, list)) \
            else tuple(jnp.ones_like(o) for o in out)
    else:
        cot = _unwrap(v)
    grads = vjp_fn(cot)
    return _wrap(out), _wrap(grads if len(arrays) > 1 else grads[0])
