"""PyLayer: user-defined autograd ops (reference:
`python/paddle/autograd/py_layer.py`, C++ side `fluid/eager/pylayer/`).

The custom backward plugs straight into the GradNode tape as a node whose
vjp is the user's ``backward`` static method.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor, GradNode, is_grad_enabled, no_grad

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    def saved_tensor(self):
        return self._saved

    def mark_not_inplace(self, *args):
        pass

    def mark_non_differentiable(self, *args):
        self._non_diff = args

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = bool(value)


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)] + \
                        [v for v in kwargs.values() if isinstance(v, Tensor)]
        need_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)

        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        if not need_grad:
            return outputs

        is_multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if is_multi else [outputs]
        diff_inputs = [t for t in tensor_inputs if not t.stop_gradient
                       and jnp.issubdtype(t.dtype, jnp.inexact)]
        out_avals = [(tuple(o.shape), o.dtype) for o in outs]

        def _align(grads, wrap, zeros):
            """Align user-backward grads with *all* tensor inputs, then select
            the differentiable ones (paddle: backward returns one grad per
            input)."""
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            grad_map = {}
            gi = 0
            for t in tensor_inputs:
                if gi < len(grads):
                    grad_map[id(t)] = grads[gi]
                    gi += 1
            return tuple(
                zeros(t) if grad_map.get(id(t)) is None
                else wrap(grad_map[id(t)])
                for t in diff_inputs)

        def vjp_fn(cotangents):
            cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
            ct_tensors = [Tensor(c, stop_gradient=True) for c in cts]
            grads = cls.backward(ctx, *ct_tensors)
            return _align(
                grads,
                wrap=lambda g: g._data if isinstance(g, Tensor) else jnp.asarray(g),
                zeros=lambda t: jnp.zeros(tuple(t.shape), t.dtype))

        def replay_fn(ct_tensors):
            """Tensor-level backward for create_graph: runs the user's
            backward on live Tensors so its ops record their own tape."""
            grads = cls.backward(ctx, *ct_tensors)
            return _align(
                grads,
                wrap=lambda g: g if isinstance(g, Tensor) else Tensor(g),
                zeros=lambda t: Tensor(jnp.zeros(tuple(t.shape), t.dtype)))

        node = GradNode(cls.__name__, vjp_fn, diff_inputs, len(outs), out_avals,
                        replay_fn=replay_fn)
        for i, o in enumerate(outs):
            if isinstance(o, Tensor) and jnp.issubdtype(o.dtype, jnp.inexact):
                o.stop_gradient = False
                o._node = node
                o._out_index = i
        return outputs
