"""Autograd public API (reference: `python/paddle/autograd/`)."""

from ..framework.autograd_engine import backward, grad  # noqa: F401
from ..framework.tensor import no_grad, enable_grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .functional import jacobian, hessian, jvp, vjp  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext", "jacobian",
           "hessian", "jvp", "vjp"]
