"""``paddle.fft`` — discrete Fourier transforms.

Reference: `python/paddle/fft.py` (fft/ifft/rfft/... with norm modes).
TPU-native backend: ``jnp.fft`` — XLA lowers FFTs to its native
DFT/real-DFT HLOs. All transforms record on the tape (jax's fft has a
VJP), so spectral losses differentiate.
"""

from __future__ import annotations

import jax.numpy as jnp

from .framework.tensor import run_op

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
           "fft2", "ifft2", "rfft2", "irfft2",
           "fftn", "ifftn", "rfftn", "irfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _wrap1(opname, jfn, has_n=True):
    if has_n:
        def op(x, n=None, axis=-1, norm="backward", name=None):
            return run_op(opname, lambda a: jfn(a, n=n, axis=axis,
                                                norm=norm), (x,))
    else:
        def op(x, axes=None, name=None):
            return run_op(opname, lambda a: jfn(a, axes=axes), (x,))
    op.__name__ = opname
    return op


fft = _wrap1("fft", jnp.fft.fft)
ifft = _wrap1("ifft", jnp.fft.ifft)
rfft = _wrap1("rfft", jnp.fft.rfft)
irfft = _wrap1("irfft", jnp.fft.irfft)
hfft = _wrap1("hfft", jnp.fft.hfft)
ihfft = _wrap1("ihfft", jnp.fft.ihfft)
fftshift = _wrap1("fftshift", jnp.fft.fftshift, has_n=False)
ifftshift = _wrap1("ifftshift", jnp.fft.ifftshift, has_n=False)


def _wrap2(opname, jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return run_op(opname, lambda a: jfn(a, s=s, axes=axes, norm=norm),
                      (x,))
    op.__name__ = opname
    return op


fft2 = _wrap2("fft2", jnp.fft.fft2)
ifft2 = _wrap2("ifft2", jnp.fft.ifft2)
rfft2 = _wrap2("rfft2", jnp.fft.rfft2)
irfft2 = _wrap2("irfft2", jnp.fft.irfft2)


def _wrapn(opname, jfn):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return run_op(opname, lambda a: jfn(a, s=s, axes=axes, norm=norm),
                      (x,))
    op.__name__ = opname
    return op


fftn = _wrapn("fftn", jnp.fft.fftn)
ifftn = _wrapn("ifftn", jnp.fft.ifftn)
rfftn = _wrapn("rfftn", jnp.fft.rfftn)
irfftn = _wrapn("irfftn", jnp.fft.irfftn)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.tensor import Tensor
    return Tensor(jnp.fft.fftfreq(n, d).astype(dtype or "float32"))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.tensor import Tensor
    return Tensor(jnp.fft.rfftfreq(n, d).astype(dtype or "float32"))
