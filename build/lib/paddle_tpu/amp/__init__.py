"""Automatic mixed precision.

Reference: `python/paddle/amp/auto_cast.py:1` (``auto_cast``/``decorate``)
and `grad_scaler.py:1` (``GradScaler``); op policy data from
`amp_lists.py`. TPU-native defaults: dtype is **bfloat16** (the MXU's
native input format — no loss scaling required) and the policy is applied
at the single eager-dispatch seam (`framework/amp_state.py`) instead of
being code-generated into every op.

O1: white-list ops (matmul-class) run in bf16, black-list ops in fp32,
the rest follow their inputs. O2: additionally ``decorate`` casts model
parameters to bf16 (norm layers stay fp32) and turns on master weights in
the optimizer (fp32 copies updated by the fp32 step, params re-quantized
each step — the existing ``multi_precision`` machinery in
`optimizer/optimizer.py`).
"""

from __future__ import annotations

import functools

import numpy as np

from ..framework import amp_state
from ..framework.dtype import convert_dtype
from . import amp_lists
from .amp_lists import WHITE_LIST, BLACK_LIST, white_list, black_list
from .grad_scaler import GradScaler, AmpScaler

__all__ = ["auto_cast", "amp_guard", "decorate", "amp_decorate",
           "GradScaler", "AmpScaler", "is_bfloat16_supported",
           "is_float16_supported", "WHITE_LIST", "BLACK_LIST"]


def is_bfloat16_supported(device=None):
    return True  # bf16 is native on TPU and emulated losslessly on CPU


def is_float16_supported(device=None):
    import jax
    return jax.default_backend() == "tpu"


class auto_cast:
    """Context manager (or decorator) enabling autocast inside the region.

    ``auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
    level='O1', dtype='bfloat16')`` — the reference's signature
    (`amp/auto_cast.py`) with the TPU-first default dtype. Nesting works;
    ``enable=False`` disables AMP inside an enabled region.
    """

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16",
                 use_promote=True):
        if level not in ("O0", "O1", "O2"):
            raise ValueError(f"level must be O0/O1/O2, got {level!r}")
        self._enable = bool(enable) and level != "O0"
        self._attrs = None
        if self._enable:
            dt = np.dtype(convert_dtype(dtype))
            if dt.name not in ("float16", "bfloat16"):
                raise ValueError(
                    f"auto_cast dtype must be float16/bfloat16, got {dtype}")
            self._attrs = amp_state.AmpAttrs(
                dt, level,
                white_list(custom_white_list, custom_black_list),
                black_list(custom_white_list, custom_black_list))
        else:
            # explicit disable: a no-op state shadowing any outer one
            self._attrs = amp_state.AmpAttrs(
                np.dtype("float32"), "O0", frozenset(), frozenset())

    def __enter__(self):
        amp_state.push(self._attrs)
        return self

    def __exit__(self, *exc):
        amp_state.pop()
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with self:
                return fn(*args, **kwargs)
        return wrapped


amp_guard = auto_cast  # legacy alias (reference: base/dygraph/amp/auto_cast)


def _norm_like(layer):
    from ..nn.layer import norm as N
    keep = (N.LayerNorm, N.RMSNorm, N._BatchNormBase, N.GroupNorm,
            N._InstanceNormBase, N.LocalResponseNorm)
    return isinstance(layer, keep)


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None, **kwargs):
    """O2 model decoration: cast float params to ``dtype`` in place (norm
    layers keep fp32 params) and enable optimizer master weights.

    Reference: `python/paddle/amp/auto_cast.py` ``decorate``. Returns
    (models, optimizers) in the same single-or-list structure it was given.
    """
    from ..nn import Layer

    if level not in ("O1", "O2"):
        raise ValueError(f"decorate level must be O1/O2, got {level!r}")
    model_list = models if isinstance(models, (list, tuple)) else [models]
    opt_list = () if optimizers is None else (
        optimizers if isinstance(optimizers, (list, tuple)) else [optimizers])

    if level == "O2":
        dt = np.dtype(convert_dtype(dtype))
        for m in model_list:
            if not isinstance(m, Layer):
                raise TypeError("decorate expects paddle_tpu.nn.Layer models")
            for _, sub in m.named_sublayers(include_self=True):
                if _norm_like(sub):
                    continue
                for p in sub._parameters.values():
                    if p is not None and p.dtype.name == "float32":
                        p._data = p._data.astype(dt)
        for o in opt_list:
            if master_weight is not False:
                o._multi_precision = True

    if optimizers is None:
        return models
    return models, optimizers


amp_decorate = decorate

from . import debugging  # noqa: F401,E402
