"""AMP op lists — the per-op dtype policy as data.

Reference: `python/paddle/amp/amp_lists.py` (WHITE_LIST / BLACK_LIST for
fp16/bf16, O1/O2). Names here are the framework's op-registry names (the
``run_op`` dispatch names, see `paddle_tpu/tensor/registry.py` — the analog
of the reference's op types).

- WHITE: matmul-class ops that the MXU runs natively in bf16 — always
  worth casting down.
- BLACK: numerically sensitive ops (losses, log/exp family, long
  reductions) that must accumulate in float32.
- everything else ("gray") runs in whatever dtype its inputs carry.
"""

WHITE_LIST = {
    "matmul", "mm", "bmm", "addmm", "mv", "einsum", "multi_dot",
    "linear", "fused_linear",
    "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose",
    "flash_attention", "scaled_dot_product_attention",
}

BLACK_LIST = {
    # losses
    "cross_entropy", "softmax_with_cross_entropy", "nll_loss", "kl_div",
    "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "sigmoid_cross_entropy_with_logits", "mse_loss", "l1_loss",
    "smooth_l1_loss", "ctc_loss", "margin_cross_entropy",
    # log/exp family
    "log", "log2", "log10", "log1p", "exp", "expm1", "pow",
    "logsumexp", "log_softmax", "softmax",
    # long reductions / norms (bf16 accumulation drifts)
    "sum", "mean", "cumsum", "norm", "p_norm", "var", "std", "dist",
    "erfinv", "cosh", "sinh", "acos", "asin",
}


def white_list(custom_white=None, custom_black=None):
    w = set(WHITE_LIST)
    if custom_white:
        w |= set(custom_white)
    if custom_black:
        w -= set(custom_black)
    return w


def black_list(custom_white=None, custom_black=None):
    b = set(BLACK_LIST)
    if custom_black:
        b |= set(custom_black)
    if custom_white:
        b -= set(custom_white)
    return b
