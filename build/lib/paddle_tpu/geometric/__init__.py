"""``paddle.geometric`` — graph message passing.

Reference: `python/paddle/geometric/message_passing/send_recv.py`
(``send_u_recv``/``send_ue_recv``/``send_uv``) and `math.py`
(``segment_sum/mean/max/min``). TPU-native backend: ``jax.ops.segment_*``
— XLA lowers segment reductions to sorted scatter-adds that ride the
VPU; gather/scatter indices are data, so everything traces under jit and
differentiates through the tape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, run_op

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_max", "segment_min"]

_SEG = {
    "sum": jax.ops.segment_sum,
    "max": jax.ops.segment_max,
    "min": jax.ops.segment_min,
}

_COMBINE = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
            "div": jnp.divide}


def _reduce(msgs, ids, num_segments, op):
    """THE segment reduction (shared by every public op): paddle
    semantics — mean divides by counts, empty max/min segments fill 0
    (jax fills +-inf). Counts only computed when the op needs them."""
    def counts():
        c = jax.ops.segment_sum(jnp.ones_like(ids, jnp.float32), ids,
                                num_segments)
        return c.reshape((-1,) + (1,) * (msgs.ndim - 1))

    if op == "mean":
        return jax.ops.segment_sum(msgs, ids, num_segments) \
            / jnp.maximum(counts(), 1.0)
    out = _SEG[op](msgs, ids, num_segments)
    if op in ("max", "min"):
        out = jnp.where(counts() == 0, jnp.zeros_like(out), out)
    return out


def _segment(name, data, ids, num_segments):
    return run_op(f"segment_{name}",
                  lambda x, i: _reduce(x, i, num_segments, name),
                  (data, ids))


def segment_sum(data, segment_ids, num_segments=None, name=None):
    """Reference geometric/math.py segment_sum."""
    n = _num_segments(segment_ids, num_segments)
    return _segment("sum", data, segment_ids, n)


def segment_mean(data, segment_ids, num_segments=None, name=None):
    n = _num_segments(segment_ids, num_segments)
    return _segment("mean", data, segment_ids, n)


def segment_max(data, segment_ids, num_segments=None, name=None):
    n = _num_segments(segment_ids, num_segments)
    return _segment("max", data, segment_ids, n)


def segment_min(data, segment_ids, num_segments=None, name=None):
    n = _num_segments(segment_ids, num_segments)
    return _segment("min", data, segment_ids, n)


def _num_segments(ids, num_segments):
    if num_segments is not None:
        return int(num_segments)
    arr = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
    return int(arr.max()) + 1   # eager-only convenience; pass it under jit


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather features at ``src_index``, reduce onto ``dst_index``
    (reference send_recv.py send_u_recv)."""
    n = out_size if out_size is not None else (
        x.shape[0] if isinstance(x, Tensor) else jnp.asarray(x).shape[0])

    def fn(xa, s, d):
        return _reduce(xa[s], d, n, reduce_op)

    return run_op("send_u_recv", fn, (x, src_index, dst_index))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Node features combined with edge features, then reduced
    (reference send_ue_recv)."""
    n = out_size if out_size is not None else (
        x.shape[0] if isinstance(x, Tensor) else jnp.asarray(x).shape[0])
    combine = _COMBINE[message_op]

    def fn(xa, ya, s, d):
        return _reduce(combine(xa[s], ya), d, n, reduce_op)

    return run_op("send_ue_recv", fn, (x, y, src_index, dst_index))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Edge messages from both endpoints (reference send_uv)."""
    combine = _COMBINE[message_op]

    def fn(xa, ya, s, d):
        return combine(xa[s], ya[d])

    return run_op("send_uv", fn, (x, y, src_index, dst_index))


def segment_pool(data, segment_ids, pool_type="sum", name=None):
    """Legacy unified segment op (reference op `segment_pool`):
    dispatches to segment_{sum,mean,max,min}."""
    fn = {"sum": segment_sum, "mean": segment_mean, "max": segment_max,
          "min": segment_min}[pool_type.lower()]
    return fn(data, segment_ids)
