"""Distributed process environment.

Reference: `python/paddle/distributed/parallel.py:943` (init_parallel_env,
env-var bootstrap over TCPStore). TPU-native: multi-host bootstrap is
``jax.distributed.initialize`` (coordination service over DCN — the
TCPStore analog); intra-host chips need no process group at all because
GSPMD compiles collectives over ICI.
"""

from __future__ import annotations

import os

import jax

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
           "is_initialized"]

_initialized = False


def init_parallel_env():
    """Bootstrap multi-host execution.

    Single-process (the common TPU pattern: one process per host, all local
    chips visible) needs no setup. Multi-host reads the reference-shaped env
    vars (``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM`` /
    ``PADDLE_MASTER``) or the JAX-native ones, then starts the coordination
    service.
    """
    global _initialized
    if _initialized:
        return ParallelEnv()
    # same helper the import-time worker bootstrap uses (one
    # implementation: gloo-on-cpu config + coordinator join, idempotent).
    # This late path only works if nothing initialized the XLA backend
    # yet — prefer launching via the CLI, which bootstraps at import.
    from .._bootstrap import bootstrap_distributed
    bootstrap_distributed()
    _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    return jax.process_index()


def get_world_size(group=None):
    return jax.process_count()


class ParallelEnv:
    """Reference: `python/paddle/distributed/parallel.py` ParallelEnv."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()
