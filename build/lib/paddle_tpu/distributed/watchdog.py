"""Failure detection: step watchdog + elastic membership manager.

Reference: the NCCL comm watchdog (`phi/core/distributed/
comm_task_manager.h:37`, timeout detection `comm_task.h:127` — a
background loop that flags hung collectives) and elastic training
(`fleet/elastic/manager.py:124`, watch-loop `:594` — membership
tracking with scale-up/down detection and relaunch).

TPU-native shape: collectives are compiled into the XLA program, so a
hang surfaces as a step that never completes — the watchdog therefore
monitors STEP HEARTBEATS from the host side (the granularity that
exists on TPU), firing a callback / logging / aborting when the gap
exceeds the timeout. ElasticManager tracks expected vs live hosts via a
pluggable store (dict / file-based for tests; etcd-shaped interface)
and reports scale events so a supervisor can checkpoint + relaunch.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = ["StepWatchdog", "ElasticManager", "FileStore"]


class StepWatchdog:
    """Host-side hang detector. ``beat()`` after every step; if no beat
    arrives within ``timeout`` seconds, ``on_timeout(gap)`` fires (once
    per stall). Reference analog: CommTaskManager's timeout loop."""

    def __init__(self, timeout=300.0, on_timeout=None, poll=None,
                 abort=False):
        self.timeout = float(timeout)
        self.on_timeout = on_timeout
        self.abort = abort
        self._poll = poll or min(1.0, self.timeout / 4)
        self._last = None
        self._fired = False
        self._stop = threading.Event()
        self._thread = None
        self.timeouts = 0

    def start(self):
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def beat(self):
        self._last = time.monotonic()
        self._fired = False

    def _loop(self):
        while not self._stop.wait(self._poll):
            if self._last is None or self._fired:
                continue
            gap = time.monotonic() - self._last
            if gap > self.timeout:
                self._fired = True
                self.timeouts += 1
                if self.on_timeout is not None:
                    self.on_timeout(gap)
                if self.abort:
                    os._exit(124)   # the reference aborts hung workers

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class FileStore:
    """Shared-filesystem membership store (the test/simple deployment
    analog of the reference's ETCD registry)."""

    def __init__(self, path):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def register(self, host_id):
        with open(os.path.join(self.path, str(host_id)), "w") as f:
            f.write(str(time.time()))

    def deregister(self, host_id):
        try:
            os.remove(os.path.join(self.path, str(host_id)))
        except FileNotFoundError:
            pass

    def hosts(self):
        return sorted(os.listdir(self.path))


class ElasticManager:
    """Membership watch-loop (reference elastic/manager.py:124).

    ``watch_once()`` compares live membership against the expected world
    and returns one of "normal" / "scale_down" / "scale_up"; ``watch``
    loops until a scale event or stop. A supervisor reacts by
    checkpointing (distributed.checkpoint) and relaunching with the new
    world size — the reference's recovery model.
    """

    def __init__(self, store, host_id, expected_hosts,
                 on_scale_event=None):
        self.store = store
        self.host_id = str(host_id)
        self.expected = int(expected_hosts)
        self.on_scale_event = on_scale_event
        self._stop = threading.Event()

    def register(self):
        self.store.register(self.host_id)
        return self

    def deregister(self):
        self.store.deregister(self.host_id)

    def watch_once(self):
        live = self.store.hosts()
        if len(live) < self.expected:
            return "scale_down"
        if len(live) > self.expected:
            return "scale_up"
        return "normal"

    def watch(self, interval=1.0, max_iters=None):
        i = 0
        while not self._stop.is_set():
            state = self.watch_once()
            if state != "normal":
                if self.on_scale_event is not None:
                    self.on_scale_event(state, self.store.hosts())
                return state
            i += 1
            if max_iters is not None and i >= max_iters:
                return "normal"
            time.sleep(interval)
        return "stopped"

    def stop(self):
        self._stop.set()
