"""Point-to-point communication over a mesh axis.

Reference: `python/paddle/distributed/fleet/meta_parallel/pp_utils/
p2p_communication.py` (send/recv between pipeline stages over NCCL) and
`fluid/distributed/collective/process_group.h:47` (send/recv tasks).

TPU-native mechanics: there are no per-rank NCCL endpoints — point-to-point
transfers between neighbouring pipeline stages are ``lax.ppermute`` on the
mesh axis, which XLA lowers to a collective-permute riding the ICI ring.
These helpers are only meaningful *inside* an SPMD region (``shard_map``
over the pipeline axis); the schedule library (`distributed.pipeline`)
calls them from its per-stage step functions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, run_op

__all__ = ["shift", "send_forward", "send_backward", "ppermute",
           "axis_rank", "axis_size"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def ppermute(x, axis_name, perm):
    """Raw collective-permute: ``perm`` is a list of (src, dst) pairs.
    Ranks not named as a dst receive zeros (XLA collective-permute
    semantics, matching the reference's recv-into-empty-buffer)."""
    if isinstance(x, Tensor):
        return run_op("ppermute",
                      lambda a: jax.lax.ppermute(a, axis_name, perm), (x,))
    return jax.lax.ppermute(x, axis_name, perm)


def shift(x, axis_name, offset=1, wrap=False):
    """Every rank i sends ``x`` to rank i+offset (receives from i-offset).

    ``wrap=False`` (pipeline semantics): edge ranks receive zeros.
    ``wrap=True`` (ring semantics, for ring attention): indices mod n.
    """
    n = jax.lax.psum(1, axis_name)
    if wrap:
        perm = [(i, (i + offset) % n) for i in range(n)]
    else:
        perm = [(i, i + offset) for i in range(n)
                if 0 <= i + offset < n]
    return ppermute(x, axis_name, perm)


def send_forward(x, axis_name):
    """Stage i -> stage i+1 (activation flow in 1F1B forward)."""
    return shift(x, axis_name, offset=1, wrap=False)


def send_backward(x, axis_name):
    """Stage i -> stage i-1 (gradient flow in 1F1B backward)."""
    return shift(x, axis_name, offset=-1, wrap=False)


def axis_rank(axis_name):
    return jax.lax.axis_index(axis_name)


def axis_size(axis_name):
    return jax.lax.psum(1, axis_name)
