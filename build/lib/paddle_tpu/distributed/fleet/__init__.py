"""``paddle.distributed.fleet`` — the hybrid-parallel orchestration API.

Reference: `python/paddle/distributed/fleet/fleet.py:100` (``Fleet`` with
init/distributed_model/distributed_optimizer), `base/topology.py:178`
(``HybridCommunicateGroup`` carving the world into
data/pipe/sharding/sep/model axes) and
`base/distributed_strategy.py` (``DistributedStrategy`` knobs).

TPU-native re-design: the N-D rank topology IS a ``ProcessMesh`` — there
are no per-axis NCCL communicator groups to create; GSPMD materializes
each axis's collectives from shardings. ``fleet.init`` bootstraps the
(possibly multi-host) runtime and builds the mesh from the strategy's
parallel degrees; ``distributed_model``/``distributed_optimizer`` apply
the placement recipes (DataParallel input sharding, shard_optimizer
state inheritance).
"""

from __future__ import annotations

import numpy as np

from ..process_mesh import ProcessMesh, set_mesh, get_mesh
from ..env import init_parallel_env, get_rank, get_world_size
from .. import api as _api

__all__ = ["DistributedStrategy", "HybridCommunicateGroup", "Fleet",
           "init", "fleet", "build_topology", "utils", "recompute"]

from ..recompute import recompute as _recompute_fn


class utils:
    """fleet.utils namespace (reference fleet/utils) — recompute lives
    here in the reference's public API."""
    recompute = staticmethod(_recompute_fn)


recompute = _recompute_fn


class DistributedStrategy:
    """Parallelism knobs (reference base/distributed_strategy.py, the
    protobuf-backed strategy). Only the fields that mean something on TPU
    carry behavior; the rest are accepted for API parity."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 0,   # 0 = infer from world size / other degrees
            "mp_degree": 1,
            "pp_degree": 1,
            "sharding_degree": 1,
            "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.gradient_merge = False
        self.pipeline = False
        self.find_unused_parameters = False


def build_topology(strategy=None, world_size=None):
    """Strategy degrees -> ProcessMesh with the reference's axis order
    (pp, mp, sep, sharding, dp — `topology.py:290`), dropping size-1
    axes. Unset degrees absorb the remaining world into dp."""
    if world_size is not None:
        world = world_size
    else:
        # the topology spans DEVICES, not processes: one TPU process
        # drives every local chip (global view across all hosts)
        import jax
        world = len(jax.devices())
    cfg = (strategy or DistributedStrategy()).hybrid_configs
    degrees = [("pp", cfg.get("pp_degree", 1)),
               ("mp", cfg.get("mp_degree", 1)),
               ("sep", cfg.get("sep_degree", 1)),
               ("sharding", cfg.get("sharding_degree", 1)),
               ("dp", cfg.get("dp_degree", 0) or 0)]
    known = 1
    for name, d in degrees[:-1]:
        known *= max(1, d)
    dp = degrees[-1][1]
    if not dp:
        if world % known:
            raise ValueError(
                f"world size {world} not divisible by configured degrees "
                f"(product {known})")
        dp = world // known
    total = known * dp
    if total != world:
        raise ValueError(
            f"degrees multiply to {total} but world size is {world}")
    names, shape = [], []
    for name, d in degrees[:-1] + [("dp", dp)]:
        d = max(1, d)
        if d > 1:
            names.append(name)
            shape.append(d)
    if not names:
        names, shape = ["dp"], [1]
    mesh = ProcessMesh(np.arange(world).reshape(shape), dim_names=names)
    return mesh


class HybridCommunicateGroup:
    """Axis-rank bookkeeping over the mesh (reference topology.py:178).
    On TPU it answers "where am I on each axis" — there are no
    communicator groups to hand out."""

    def __init__(self, mesh: ProcessMesh):
        self._mesh = mesh

    @property
    def topology(self):
        return self._mesh

    def _axis_rank(self, axis):
        if axis not in self._mesh.dim_names:
            return 0
        # the mesh holds global DEVICE ids; locate this process by its
        # first local device (process_index would misplace multi-host)
        import jax
        did = jax.local_devices()[0].id
        rank = self._mesh.get_rank_by_dim_and_process_id(axis, did)
        return max(0, int(rank))

    def _axis_size(self, axis):
        if axis not in self._mesh.dim_names:
            return 1
        return self._mesh.get_dim_size(axis)

    def get_data_parallel_rank(self):
        return self._axis_rank("dp")

    def get_data_parallel_world_size(self):
        return self._axis_size("dp")

    def get_model_parallel_rank(self):
        return self._axis_rank("mp")

    def get_model_parallel_world_size(self):
        return self._axis_size("mp")

    def get_stage_id(self):
        return self._axis_rank("pp")

    def get_pipe_parallel_world_size(self):
        return self._axis_size("pp")

    def get_sharding_parallel_rank(self):
        return self._axis_rank("sharding")

    def get_sharding_parallel_world_size(self):
        return self._axis_size("sharding")


class Fleet:
    """Reference fleet.py:100."""

    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._mesh = None

    def init(self, role_maker=None, is_collective=True, strategy=None):
        self._strategy = strategy or DistributedStrategy()
        init_parallel_env()
        self._mesh = build_topology(self._strategy)
        set_mesh(self._mesh)
        self._hcg = HybridCommunicateGroup(self._mesh)
        return self

    @property
    def strategy(self):
        return self._strategy

    def get_hybrid_communicate_group(self):
        return self._hcg

    def mesh(self):
        return self._mesh

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_first_worker(self):
        return get_rank() == 0

    def distributed_model(self, model):
        """DP wrapper: with a dp axis in the topology, inputs shard over
        it (reference: paddle.DataParallel + EagerReducer — grad
        all-reduce is GSPMD's job here)."""
        from ..parallel import DataParallel
        if self._mesh is not None and "dp" in self._mesh.dim_names \
                and self._mesh.get_dim_size("dp") > 1:
            return DataParallel(model, mesh=self._mesh, dp_axis="dp")
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        from ..api import shard_optimizer
        return shard_optimizer(optimizer)

    def is_worker(self):
        """Collective mode has no PS roles: every process is a worker."""
        return True

    def barrier_worker(self):
        from ..collective import barrier
        barrier()


fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None):
    return fleet.init(role_maker, is_collective, strategy)


# module-level delegators over the singleton — the reference's usage
# surface (`fleet.distributed_model(model)` etc., fleet/fleet.py:100)
def distributed_model(model):
    return fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return fleet.distributed_optimizer(optimizer, strategy)


def get_hybrid_communicate_group():
    return fleet.get_hybrid_communicate_group()


def worker_index():
    return fleet.worker_index()


def worker_num():
    return fleet.worker_num()


def is_first_worker():
    return fleet.is_first_worker()


def is_worker():
    return fleet.is_worker()


def barrier_worker():
    return fleet.barrier_worker()


class PaddleCloudRoleMaker:
    """Role shim (reference `fleet/base/role_maker.py`): collective mode
    reads ranks from the env/runtime, so the role maker is an inert
    marker object accepted by ``fleet.init`` for API parity."""

    def __init__(self, is_collective=True, **kwargs):
        self.is_collective = is_collective


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, is_collective=True, init_gloo=False, **kwargs):
        super().__init__(is_collective)


__all__ += ["distributed_model", "distributed_optimizer",
            "get_hybrid_communicate_group", "worker_index", "worker_num",
            "is_first_worker", "is_worker", "barrier_worker",
            "PaddleCloudRoleMaker", "UserDefinedRoleMaker"]
