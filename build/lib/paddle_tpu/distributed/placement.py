"""Placement types: Shard / Replicate / Partial.

Reference: `paddle/phi/core/distributed/auto_parallel/placement_types.h`
(via `python/paddle/distributed/__init__.py`). A placements list has one
entry per *mesh* dimension describing how the tensor relates to that mesh
axis; the conversion to ``jax.sharding.PartitionSpec`` (one entry per
*tensor* dimension naming mesh axes) lives in ``api._to_partition_spec``.
"""

from __future__ import annotations

__all__ = ["Placement", "Shard", "Replicate", "Partial"]


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    """Tensor dimension ``dim`` is split across this mesh axis."""

    def __init__(self, dim):
        self.dim = int(dim)

    def get_dim(self):
        return self.dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    """Pending reduction along this mesh axis (reference: partial_status).

    Materializes only inside ``shard_map`` regions — resharding a Partial
    tensor to Replicate inserts the ``psum`` over the axis.
    """

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __eq__(self, other):
        return isinstance(other, Partial) and \
            other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"
