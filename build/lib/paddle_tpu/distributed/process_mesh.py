"""ProcessMesh over ``jax.sharding.Mesh``.

Reference: `python/paddle/distributed/auto_parallel/process_mesh.py`
(``ProcessMesh(mesh, dim_names)``). TPU-native: the mesh IS the JAX device
mesh; axis names ('dp','fsdp','sep','tp','pp','ep') drive GSPMD
sharding propagation instead of per-axis NCCL communicator groups.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["ProcessMesh", "get_mesh", "set_mesh", "init_mesh"]

_global_mesh = None


class ProcessMesh:
    def __init__(self, mesh, dim_names=None, process_ids=None):
        arr = np.asarray(mesh)
        if arr.dtype.kind not in "iu":
            raise TypeError("mesh must be an integer array of process ids")
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError(
                f"dim_names {dim_names} does not match mesh rank {arr.ndim}")
        self._ids = arr
        self._dim_names = list(dim_names)
        self._jax_mesh = None
        self._jax_mesh_key = None

    # -- reference API surface ---------------------------------------------
    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    @property
    def mesh(self):
        return self._ids

    def get_dim_size(self, name):
        return self._ids.shape[self._dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, dim, process_id):
        axis = self._dim_names.index(dim) if isinstance(dim, str) else dim
        pos = np.argwhere(self._ids == process_id)
        return int(pos[0][axis]) if len(pos) else -1

    def __eq__(self, other):
        return isinstance(other, ProcessMesh) and \
            np.array_equal(self._ids, other._ids) and \
            self._dim_names == other._dim_names

    def __hash__(self):
        return hash((self._ids.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, "
                f"dim_names={self._dim_names})")

    # -- JAX bridge ---------------------------------------------------------
    def to_jax_mesh(self):
        """Materialize as ``jax.sharding.Mesh`` over the visible devices.

        The cache is keyed on the visible device list so a mesh built
        before ``jax.distributed.initialize`` (or a backend switch) is
        rebuilt rather than silently reusing stale devices."""
        devices = jax.devices()
        key = tuple(id(d) for d in devices)
        if self._jax_mesh is None or self._jax_mesh_key != key:
            dev_np = np.asarray(devices)
            flat = self._ids.reshape(-1)
            if flat.max() >= len(dev_np):
                raise RuntimeError(
                    f"mesh references process id {int(flat.max())} but only "
                    f"{len(dev_np)} devices are visible")
            dev_arr = dev_np[flat].reshape(self._ids.shape)
            self._jax_mesh = Mesh(dev_arr, tuple(self._dim_names))
            self._jax_mesh_key = key
        return self._jax_mesh

    def __enter__(self):
        self.to_jax_mesh().__enter__()
        return self

    def __exit__(self, *exc):
        return self._jax_mesh.__exit__(*exc)


def init_mesh(shape, dim_names):
    """Build a ProcessMesh spanning all visible devices (helper, analog of
    `fleet.base.topology.CommunicateTopology` construction)."""
    n = int(np.prod(shape))
    ids = np.arange(n).reshape(shape)
    return ProcessMesh(ids, dim_names)


def set_mesh(mesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh():
    return _global_mesh
