"""Tensor-parallel (Megatron-style) layers.

Reference: `python/paddle/distributed/fleet/layers/mpu/mp_layers.py`
(`VocabParallelEmbedding:47`, `ColumnParallelLinear:334`,
`RowParallelLinear:541`). TPU-native: instead of manual c_identity /
mp_allreduce PyLayers around per-rank matmuls, the layer *annotates its
weight with a sharding* over the mesh's model-parallel axis and lets GSPMD
insert the all-gather/reduce-scatter where the propagation needs it —
the compiler reproduces exactly the Megatron comm pattern (column: free;
row: psum on output) but can also overlap it with compute.
"""

from __future__ import annotations

import numpy as np

from .. import nn
from .api import shard_tensor
from .placement import Shard, Replicate

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy"]


def _mp_axis_index(mesh, axis_name):
    if axis_name not in mesh.dim_names:
        raise ValueError(
            f"mesh {mesh} has no axis {axis_name!r}")
    return mesh.dim_names.index(axis_name)


def _placements(mesh, mesh_dim, shard_tensor_dim):
    out = [Replicate()] * mesh.ndim
    out[mesh_dim] = Shard(shard_tensor_dim)
    return out


class ColumnParallelLinear(nn.Layer):
    """Weight [in, out] sharded along out (reference mp_layers.py:334).

    With ``gather_output=False`` the activation stays sharded on its last
    dim — feed it to a RowParallelLinear, GSPMD keeps everything local
    until the row matmul's psum, the Megatron fusion.
    """

    def __init__(self, in_features, out_features, mesh, axis_name="mp",
                 weight_attr=None, has_bias=True, gather_output=True,
                 name=None):
        super().__init__()
        self.linear = nn.Linear(in_features, out_features,
                                weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self.mesh = mesh
        self.gather_output = gather_output
        md = _mp_axis_index(mesh, axis_name)
        self.linear.weight = shard_tensor(
            self.linear.weight, mesh, _placements(mesh, md, 1))
        if has_bias:
            self.linear.bias = shard_tensor(
                self.linear.bias, mesh, _placements(mesh, md, 0))

    def forward(self, x):
        return self.linear(x)


class RowParallelLinear(nn.Layer):
    """Weight [in, out] sharded along in (reference mp_layers.py:541); the
    matmul's contraction over the sharded dim makes GSPMD emit the
    all-reduce the reference codes by hand."""

    def __init__(self, in_features, out_features, mesh, axis_name="mp",
                 weight_attr=None, has_bias=True, input_is_parallel=False,
                 name=None):
        super().__init__()
        self.linear = nn.Linear(in_features, out_features,
                                weight_attr=weight_attr,
                                bias_attr=None if has_bias else False)
        self.mesh = mesh
        md = _mp_axis_index(mesh, axis_name)
        self.linear.weight = shard_tensor(
            self.linear.weight, mesh, _placements(mesh, md, 0))
        if has_bias:
            self.linear.bias = shard_tensor(
                self.linear.bias, mesh, [Replicate()] * mesh.ndim)

    def forward(self, x):
        return self.linear(x)


class VocabParallelEmbedding(nn.Layer):
    """Embedding table sharded along vocab (reference mp_layers.py:47)."""

    def __init__(self, num_embeddings, embedding_dim, mesh, axis_name="mp",
                 weight_attr=None, name=None):
        super().__init__()
        self.embedding = nn.Embedding(num_embeddings, embedding_dim,
                                      weight_attr=weight_attr)
        md = _mp_axis_index(mesh, axis_name)
        self.embedding.weight = shard_tensor(
            self.embedding.weight, mesh, _placements(mesh, md, 0))

    def forward(self, x):
        return self.embedding(x)


class ParallelCrossEntropy(nn.Layer):
    """Reference mp_layers.py:742: cross entropy over vocab-sharded logits.
    GSPMD handles the sharded logsumexp reduction; the layer only needs the
    numerically-stable composition."""

    def __init__(self, mesh=None, axis_name="mp", ignore_index=-100,
                 name=None):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        import paddle_tpu.nn.functional as F
        return F.cross_entropy(logits, labels, reduction="none")


def _constrain(t, mesh, spec_dims):
    """Tape-recorded sharding constraint (the TPU analog of the
    reference's ScatterOp/AllGatherOp markers in
    `fleet/utils/sequence_parallel_utils.py:85,111`)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from ..framework.tensor import run_op

    ns = NamedSharding(mesh.to_jax_mesh(), PartitionSpec(*spec_dims))
    return run_op("sharding_constraint",
                  lambda a: jax.lax.with_sharding_constraint(a, ns), (t,))


def _sp_spec(ndim, axis, kind):
    """PartitionSpec dims for sequence-/head-sharded activations: 3-D
    batch-major [B, S, H] or 2-D flattened [S(*B), H] (the layout the
    reference's SP region uses)."""
    if ndim == 3:
        return (None, axis, None) if kind == "seq" else (None, None, axis)
    if ndim == 2:
        return (axis, None) if kind == "seq" else (None, axis)
    raise ValueError(
        f"sequence-parallel linear expects 2-D or 3-D activations, "
        f"got rank {ndim}")


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Megatron-SP column linear (reference
    `sequence_parallel_utils.py:395`): the incoming activation is
    SEQUENCE-sharded over the mp axis; the matmul needs the full
    sequence, so GSPMD inserts the all-gather the reference codes as
    ``AllGatherOp`` — and the output leaves head-sharded for the paired
    row layer."""

    def __init__(self, in_features, out_features, mesh, axis_name="mp",
                 weight_attr=None, has_bias=True, gather_output=False,
                 name=None):
        super().__init__(in_features, out_features, mesh, axis_name,
                         weight_attr, has_bias, gather_output, name)
        self._axis = axis_name

    def forward(self, x):
        x = _constrain(x, self.mesh, _sp_spec(x.ndim, self._axis, "seq"))
        y = self.linear(x)
        return _constrain(y, self.mesh,
                          _sp_spec(y.ndim, self._axis, "head"))


class RowSequenceParallelLinear(RowParallelLinear):
    """Megatron-SP row linear (reference
    `sequence_parallel_utils.py:528`): input arrives head-sharded, the
    contraction psum fuses with a scatter back to sequence-sharded
    output — the reference's ``ReduceScatterOp``, emitted by GSPMD as
    one reduce-scatter."""

    def __init__(self, in_features, out_features, mesh, axis_name="mp",
                 weight_attr=None, has_bias=True, input_is_parallel=True,
                 name=None):
        super().__init__(in_features, out_features, mesh, axis_name,
                         weight_attr, has_bias, input_is_parallel, name)
        self._axis = axis_name

    def forward(self, x):
        y = self.linear(x)
        return _constrain(y, self.mesh,
                          _sp_spec(y.ndim, self._axis, "seq"))


__all__ += ["ColumnSequenceParallelLinear", "RowSequenceParallelLinear"]
