"""``python -m paddle_tpu.distributed.launch`` — the process launcher.

Reference: `python/paddle/distributed/launch/main.py` +
`launch/controllers/collective.py:22` (``CollectiveController`` spawning
one process per device with ``PADDLE_*`` env, master rendezvous in
`controllers/master.py:73`).

TPU-native shape: ONE process per host (each process drives all its
local chips; intra-host needs no process group — GSPMD compiles the
collectives), so ``--nproc_per_node`` defaults to 1 and exists for
CPU-simulation runs. The launcher:

- assigns ranks ``node_rank * nproc + local``,
- exports the reference-shaped env (``PADDLE_TRAINER_ID``,
  ``PADDLE_TRAINERS_NUM``, ``PADDLE_MASTER``) that
  ``init_parallel_env`` turns into ``jax.distributed.initialize``,
- tees each worker's output to ``<log_dir>/workerlog.<rank>``,
- waits on all workers, kills the rest when any fails, and exits with
  the first failure code (the reference's watcher behavior,
  `launch/controllers/watcher.py`).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def launch(script_args, nnodes=1, node_rank=0, nproc_per_node=1,
           master=None, log_dir="log", env_extra=None):
    """Spawn workers for ``script_args`` (list: script + its argv)."""
    world = nnodes * nproc_per_node
    if nnodes > 1 and master is None:
        raise ValueError(
            "--master host:port is required for multi-node launches "
            "(a localhost default would leave non-zero nodes waiting on "
            "a coordinator that does not exist)")
    if world > 1 and master is None:
        master = "127.0.0.1:23456"
    os.makedirs(log_dir, exist_ok=True)
    procs, logs = [], []
    try:
        for local in range(nproc_per_node):
            rank = node_rank * nproc_per_node + local
            env = dict(os.environ)
            env.update(env_extra or {})
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_LOCAL_RANK": str(local),
                "PADDLE_NNODES": str(nnodes),
                "FLAGS_selected_devices": str(local),
            })
            if master:
                env["PADDLE_MASTER"] = master
            log = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable] + list(script_args),
                env=env, stdout=log, stderr=subprocess.STDOUT))
        # wait; on any failure kill the rest (reference watcher behavior)
        exit_code = 0
        pending = set(range(len(procs)))
        while pending:
            for i in sorted(pending):
                ret = procs[i].poll()
                if ret is None:
                    continue
                pending.discard(i)
                if ret != 0 and exit_code == 0:
                    exit_code = ret
                    for j in pending:
                        procs[j].send_signal(signal.SIGTERM)
            time.sleep(0.2)
        return exit_code
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.close()


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="launch multi-host paddle_tpu training")
    ap.add_argument("--nnodes", type=int, default=1)
    ap.add_argument("--node_rank", type=int,
                    default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    ap.add_argument("--nproc_per_node", type=int, default=1,
                    help="processes on this host (1 = all local chips in "
                         "one process, the TPU default)")
    ap.add_argument("--master", default=os.environ.get("PADDLE_MASTER"))
    ap.add_argument("--log_dir", default="log")
    ap.add_argument("script", nargs=argparse.REMAINDER,
                    help="training script and its arguments")
    args = ap.parse_args(argv)
    if not args.script:
        ap.error("no training script given")
    code = launch(args.script, nnodes=args.nnodes,
                  node_rank=args.node_rank,
                  nproc_per_node=args.nproc_per_node, master=args.master,
                  log_dir=args.log_dir)
    sys.exit(code)
