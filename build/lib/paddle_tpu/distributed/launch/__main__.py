from . import main

main()
