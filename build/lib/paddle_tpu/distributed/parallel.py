"""DataParallel + shard_dataloader.

Reference: `python/paddle/parallel.py` ``DataParallel`` (wrapping a model
with an ``EagerReducer`` doing bucketed grad allreduce,
`fluid/distributed/collective/reducer.h:88`) and
`auto_parallel/api.py:2597` ``shard_dataloader``.

TPU-native re-design: there is no reducer. DataParallel commits each
forward input's batch dim to the mesh's dp axis; GSPMD then keeps
activations batch-sharded and emits ONE fused gradient all-reduce per
parameter group inside the compiled step — the compiler does what the
reference's bucketing reducer does by hand, overlapped with backward
compute by XLA's scheduler.
"""

from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor
from .api import shard_tensor
from .placement import Shard, Replicate
from .process_mesh import ProcessMesh

__all__ = ["DataParallel", "shard_dataloader", "ShardDataloader"]


def _default_mesh():
    import jax
    return ProcessMesh(np.arange(len(jax.devices())), dim_names=["dp"])


class DataParallel:
    """Wraps a Layer; forward inputs are batch-sharded over ``dp_axis``.

    Usage matches the reference: ``model = paddle.DataParallel(model)``;
    attribute access forwards to the wrapped layer.
    """

    def __init__(self, layers, mesh=None, dp_axis="dp",
                 find_unused_parameters=False, **kwargs):
        self._layers = layers
        self._mesh = mesh if mesh is not None else _default_mesh()
        self._dp_axis = dp_axis
        if dp_axis not in self._mesh.dim_names:
            raise ValueError(f"mesh has no axis {dp_axis!r}")
        self._placements = [
            Shard(0) if n == dp_axis else Replicate()
            for n in self._mesh.dim_names]

    def _shard_input(self, x):
        if isinstance(x, Tensor) and x.ndim > 0 \
                and not getattr(x, "is_dist", False):
            return shard_tensor(x, self._mesh, self._placements,
                                stop_gradient=x.stop_gradient)
        return x

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_input(i) for i in inputs)
        kwargs = {k: self._shard_input(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    __call__ = forward

    # transparent passthrough (parameters(), train(), state_dict(), ...)
    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def scale_loss(self, loss):
        """Reference DataParallel.scale_loss — identity here: the mean
        over the dp-sharded batch already averages globally under
        GSPMD."""
        return loss


class ShardDataloader:
    """Iterates a DataLoader, committing each batch to the mesh
    (reference api.py:2597 shard_dataloader)."""

    def __init__(self, dataloader, meshes, shard_dims=0, input_keys=None):
        self._loader = dataloader
        self._mesh = meshes if isinstance(meshes, ProcessMesh) \
            else meshes[0]
        if isinstance(shard_dims, str):
            axis = shard_dims
        else:
            axis = self._mesh.dim_names[int(shard_dims)]
        self._input_keys = input_keys
        self._placements = [
            Shard(0) if n == axis else Replicate()
            for n in self._mesh.dim_names]

    def __len__(self):
        return len(self._loader)

    def _commit(self, item, key=None):
        if isinstance(item, dict):
            return {k: self._commit(v, key=k) for k, v in item.items()}
        if isinstance(item, (list, tuple)):
            elems = [self._commit(e, key=key) for e in item]
            if hasattr(item, "_fields"):     # namedtuple
                return type(item)(*elems)
            return type(item)(elems)
        t = item if isinstance(item, Tensor) else Tensor(np.asarray(item))
        if t.ndim == 0:
            return t
        if key is not None and self._input_keys is not None \
                and key not in self._input_keys:
            return t   # non-input entries stay unsharded
        return shard_tensor(t, self._mesh, self._placements,
                            stop_gradient=True)

    def __iter__(self):
        for batch in self._loader:
            yield self._commit(batch)


def shard_dataloader(dataloader, meshes, shard_dims=0, is_dataset=False,
                     input_keys=None):
    return ShardDataloader(dataloader, meshes, shard_dims, input_keys)
