"""Collective communication API.

Reference: `python/paddle/distributed/communication/` (all_reduce.py:20 et
al. over pybind ProcessGroup). TPU-native semantics: inside a traced SPMD
region (``shard_map`` over a mesh axis) these lower to XLA collectives on
ICI (`jax.lax.psum`/`all_gather`/`psum_scatter`/`all_to_all`/`ppermute`);
in the eager single-controller world every visible chip already
participates in GSPMD ops, so process-level collectives are identities
within one process and the multi-host boundary is handled by
``jax.distributed`` + GSPMD over DCN.

A ``group`` here is a mesh axis handle, not a communicator: collectives
name the mesh dimension they ride over, mirroring how the reference names
a HybridCommunicateGroup axis ("dp"/"mp"/"pp"/"sep"/"sharding").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, run_op

__all__ = ["ReduceOp", "Group", "new_group", "all_reduce", "all_gather",
           "all_gather_object", "reduce_scatter", "alltoall", "broadcast",
           "reduce", "scatter", "barrier", "send", "recv", "isend", "irecv",
           "wait", "get_group"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A mesh-axis communication scope (reference: communication/group.py)."""

    def __init__(self, axis_name=None, ranks=None, id=0):
        self.axis_name = axis_name
        self.ranks = ranks or []
        self.id = id

    @property
    def nranks(self):
        return len(self.ranks) if self.ranks else 1

    def __repr__(self):
        return f"Group(axis={self.axis_name}, ranks={self.ranks})"


_groups = {0: Group(axis_name=None, ranks=[0], id=0)}
_next_gid = 1


def new_group(ranks=None, backend=None, timeout=None, axis_name=None):
    global _next_gid
    g = Group(axis_name=axis_name, ranks=ranks or [], id=_next_gid)
    _groups[_next_gid] = g
    _next_gid += 1
    return g


def get_group(gid=0):
    return _groups.get(gid)


def _axis(group):
    return group.axis_name if isinstance(group, Group) else group


def _is_traced(t):
    return isinstance(t._data if isinstance(t, Tensor) else t,
                      jax.core.Tracer)


_REDUCERS = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
    ReduceOp.AVG: jax.lax.pmean,
}


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In an SPMD region: reduce over the group's mesh axis; eager
    single-process: identity (GSPMD already holds the global value)."""
    axis = _axis(group)
    if axis is not None and _is_traced(tensor):
        red = _REDUCERS[op]
        out = run_op("all_reduce", lambda x: red(x, axis), (tensor,))
        tensor._data = out._data
        return out
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    axis = _axis(group)
    if axis is not None and _is_traced(tensor):
        out = run_op(
            "all_gather",
            lambda x: jax.lax.all_gather(x, axis, tiled=False), (tensor,))
        n = out.shape[0]
        tensor_list.extend(out[i] for i in range(n))
        return out
    tensor_list.append(tensor)
    return tensor


def all_gather_object(object_list, obj, group=None):
    object_list.append(obj)


def reduce_scatter(tensor, tensor_list_or_input, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    axis = _axis(group)
    src = tensor_list_or_input
    if isinstance(src, (list, tuple)):
        from ..tensor.manipulation import concat
        src = concat(list(src), axis=0)
    if axis is not None and _is_traced(src):
        out = run_op(
            "reduce_scatter",
            lambda x: jax.lax.psum_scatter(x, axis, tiled=True), (src,))
        tensor._data = out._data
        return out
    tensor._data = src._data if isinstance(src, Tensor) else jnp.asarray(src)
    return tensor


def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    axis = _axis(group)
    if isinstance(in_tensor_list, (list, tuple)):
        from ..tensor.manipulation import stack
        stacked = stack(list(in_tensor_list), axis=0)
    else:
        stacked = in_tensor_list
    if axis is not None and _is_traced(stacked):
        out = run_op(
            "alltoall",
            lambda x: jax.lax.all_to_all(x, axis, split_axis=0,
                                         concat_axis=0, tiled=False),
            (stacked,))
        out_tensor_list.extend(out[i] for i in range(out.shape[0]))
        return out
    out_tensor_list.extend(
        in_tensor_list if isinstance(in_tensor_list, (list, tuple))
        else [in_tensor_list])
    return stacked


def broadcast(tensor, src=0, group=None, sync_op=True):
    """In an SPMD region: every rank takes rank ``src``'s value (an
    all-gather + static index, which XLA simplifies to the broadcast
    collective). Eager single-controller: identity — GSPMD arrays are
    already globally consistent."""
    axis = _axis(group)
    if axis is not None and _is_traced(tensor):
        out = run_op(
            "broadcast",
            lambda x: jax.lax.all_gather(x, axis, tiled=False)[src],
            (tensor,))
        tensor._data = out._data
        return out
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """In an SPMD region: rank i takes slice i of ``src``'s stacked input.
    (all_gather + dynamic index on axis_index; XLA folds the redundancy.)"""
    axis = _axis(group)
    if not tensor_list:
        return tensor
    from ..tensor.manipulation import stack
    stacked = stack(list(tensor_list), axis=0)
    if axis is not None and _is_traced(stacked):
        n = jax.lax.psum(1, axis)  # static: mesh axis size
        if len(tensor_list) != n:
            raise ValueError(
                f"scatter got {len(tensor_list)} tensors for a {n}-wide "
                f"axis {axis!r}; one slice per rank is required")
        def _scatter(x):
            full = jax.lax.all_gather(x, axis, tiled=False)[src]
            return full[jax.lax.axis_index(axis)]
        out = run_op("scatter", _scatter, (stacked,))
        tensor._data = out._data
        return out
    tensor._data = (tensor_list[0]._data
                    if isinstance(tensor_list[0], Tensor)
                    else jnp.asarray(tensor_list[0]))
    return tensor


def barrier(group=None):
    (jnp.zeros(()) + 0).block_until_ready()


def send(tensor, dst=0, group=None, sync_op=True):
    """Point-to-point on TPU is collective-permute on a mesh axis. Inside an
    SPMD region use :mod:`paddle_tpu.distributed.p2p` (``shift`` /
    ``send_forward`` / ``send_backward``), which every rank calls
    collectively; a one-sided eager ``send`` has no TPU equivalent."""
    raise NotImplementedError(
        "one-sided send/recv has no TPU equivalent — p2p is collective "
        "(both sides participate): inside shard_map use "
        "paddle_tpu.distributed.p2p.shift / send_forward / send_backward / "
        "ppermute from every rank of the axis")


recv = isend = irecv = send


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor) and not _is_traced(tensor):
        tensor._data.block_until_ready()
    return tensor
