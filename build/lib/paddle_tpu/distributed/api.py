"""Semi-auto parallel API: shard_tensor / reshard / shard_layer /
shard_optimizer.

Reference: `python/paddle/distributed/auto_parallel/api.py:130` (shard_tensor),
`:346` (reshard), `:445` (shard_layer), `:1120` (shard_optimizer). TPU-native
mechanics: placements convert to ``jax.sharding.NamedSharding`` and
``jax.device_put`` commits the layout; every downstream op picks shardings
up through GSPMD propagation — there is no per-op SPMD rule table to
maintain (the reference's 85 spmd_rules files collapse into the compiler).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.tensor import Tensor, Parameter
from .placement import Placement, Shard, Replicate, Partial
from .process_mesh import ProcessMesh

__all__ = ["shard_tensor", "dtensor_from_fn", "reshard", "shard_layer",
           "shard_optimizer", "unshard_dtensor", "to_partition_spec"]


def to_partition_spec(ndim, mesh, placements):
    """placements (one per MESH dim) -> PartitionSpec (one per TENSOR dim).

    The metadata transform the reference does in
    `dist_tensor.cc` TensorDistAttr <-> dims_mapping.
    """
    if len(placements) != mesh.ndim:
        raise ValueError(
            f"placements length {len(placements)} != mesh rank {mesh.ndim}")
    spec = [None] * ndim
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            d = p.get_dim()
            if d >= ndim:
                raise ValueError(
                    f"Shard(dim={d}) out of range for {ndim}-D tensor")
            name = mesh.dim_names[mesh_dim]
            if spec[d] is None:
                spec[d] = name
            elif isinstance(spec[d], tuple):
                spec[d] = spec[d] + (name,)
            else:
                spec[d] = (spec[d], name)
    return PartitionSpec(*spec)


def _named_sharding(mesh: ProcessMesh, ndim, placements):
    return NamedSharding(mesh.to_jax_mesh(),
                         to_partition_spec(ndim, mesh, placements))


def _annotate(t, mesh, placements):
    t.is_dist = True
    t._process_mesh = mesh
    t._placements = list(placements)
    return t


def shard_tensor(data, mesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    """Reference api.py:130. Returns a Tensor whose payload is committed to
    the mesh with the requested layout."""
    if not isinstance(mesh, ProcessMesh):
        raise TypeError("mesh must be a ProcessMesh")
    for p in placements:
        if isinstance(p, Partial):
            raise ValueError(
                "shard_tensor cannot materialize Partial placements; "
                "Partial arises only from op outputs inside shard_map")
    src = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    sharding = _named_sharding(mesh, src._data.ndim, placements)
    arr = jax.device_put(src._data, sharding)
    if isinstance(src, Parameter) or isinstance(data, Parameter):
        out = Parameter(arr, trainable=not src.stop_gradient)
        out.name = src.name
    else:
        sg = src.stop_gradient if stop_gradient is None else stop_gradient
        out = Tensor(arr, stop_gradient=sg)
        out.name = src.name
    return _annotate(out, mesh, placements)


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """Reference api.py dtensor_from_fn: build then shard."""
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(dist_tensor, mesh, placements):
    """Reference api.py:346. Commits the payload to a new layout —
    ``device_put`` lowers to the same collective-permute / all-gather /
    slice set as the reference's reshard function registry."""
    t = dist_tensor
    sharding = _named_sharding(mesh, t._data.ndim, placements)
    arr = jax.device_put(t._data, sharding)
    out = Tensor(arr, stop_gradient=t.stop_gradient)
    out.name = t.name
    return _annotate(out, mesh, placements)


def unshard_dtensor(dist_tensor):
    """Gather to a fully-replicated tensor (reference api.py
    unshard_dtensor)."""
    t = dist_tensor
    if not getattr(t, "is_dist", False):
        return t
    mesh = t._process_mesh
    repl = [Replicate()] * mesh.ndim
    out = reshard(t, mesh, repl)
    out.is_dist = False
    out._process_mesh = None
    out._placements = None
    return out


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """Reference api.py:445. ``shard_fn(name, layer, mesh)`` places each
    sublayer's params; default replicates everything."""
    from ..nn import Layer
    if not isinstance(layer, Layer):
        raise TypeError("layer must be a paddle_tpu.nn.Layer")

    def _replicate_params(sub):
        repl = [Replicate()] * process_mesh.ndim
        for key, p in list(sub._parameters.items()):
            if p is not None and not getattr(p, "is_dist", False):
                sub._parameters[key] = shard_tensor(p, process_mesh, repl)

    for name, sub in layer.named_sublayers(include_self=True):
        if shard_fn is not None:
            shard_fn(name, sub, process_mesh)
        _replicate_params(sub)  # anything shard_fn skipped gets replicated

    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """Reference api.py:1120. On TPU the optimizer state inherits each
    parameter's sharding automatically (the accumulator is created with
    ``zeros_like`` on the committed param), so stage-1/2 ("ZeRO") layouts
    fall out of the parameter placement; ``shard_fn(acc_name, param, acc)``
    can override per-accumulator placement."""
    from ..optimizer import Optimizer
    if not isinstance(optimizer, Optimizer):
        raise TypeError("expected a paddle_tpu Optimizer")
    if getattr(optimizer, "_shard_fn_installed", False):
        optimizer._shard_fn = shard_fn  # idempotent: update hook, don't re-wrap
        return optimizer
    orig_add = optimizer._add_accumulator
    optimizer._shard_fn = shard_fn
    optimizer._shard_fn_installed = True

    def _add(name, param, **kw):
        acc = orig_add(name, param, **kw)
        if getattr(param, "is_dist", False) and \
                acc._data.shape == param._data.shape:
            acc._data = jax.device_put(acc._data, param._data.sharding)
            _annotate(acc, param._process_mesh, param._placements)
        fn = optimizer._shard_fn
        if fn is not None:
            new = fn(name, param, acc)
            if new is not None:
                optimizer._accumulators[name][id(param)] = new
                return new
        return acc

    optimizer._add_accumulator = _add
    return optimizer
