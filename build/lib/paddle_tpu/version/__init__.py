"""``paddle.version`` (reference: generated `python/paddle/version.py`)."""

from __future__ import annotations

import subprocess

full_version = "0.1.0"
major, minor, patch = (p for p in full_version.split("."))
rc = 0
cuda_version = "False"   # reference prints the CUDA toolkit here
cudnn_version = "False"
tensorrt_version = "False"
xpu_version = "False"

istaged = False
with_pip = False


def _git_commit():
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True, timeout=5)
        return out.stdout.strip() or "unknown"
    except Exception:
        return "unknown"


commit = _git_commit()


def show():
    """Reference ``paddle.version.show()``."""
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("tpu: True (XLA/PJRT backend)")
    print(f"cuda: {cuda_version}")
    print(f"cudnn: {cudnn_version}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
