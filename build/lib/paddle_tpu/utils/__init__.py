"""``paddle.utils`` (reference: `python/paddle/utils/__init__.py`):
deprecated-API shims, install checks, and the C++ extension builder."""

from . import cpp_extension  # noqa: F401
from .lazy_import import try_import  # noqa: F401

__all__ = ["cpp_extension", "try_import", "run_check"]


def run_check():
    """Reference `utils/install_check.py:run_check` — verify the install
    can compute on the available device."""
    import jax
    import numpy as np
    from .. import to_tensor

    x = to_tensor(np.ones((2, 2), np.float32))
    y = (x @ x).numpy()
    assert (y == 2).all()
    n = len(jax.devices())
    print(f"PaddleTPU works! backend={jax.default_backend()} devices={n}")
