"""C++ custom-op extension builder (reference:
`python/paddle/utils/cpp_extension/` — ``load`` JIT-compiles user C++
into a loadable op library).

TPU-native shape: custom device kernels are Pallas (Python), so the C++
seam here is for HOST ops — data munging, tokenization, lookups — that
plug into the eager layer as ordinary Python functions. ``load`` builds
the sources with the same g++ flow as `paddle_tpu/native/build.py`
(content-hash cached .so) and binds ``extern "C"`` symbols via ctypes.
``CppExtension``/``setup`` are offered for parity with the reference's
setuptools path.

A bound symbol is called with ctypes argtypes/restype declared by the
caller, or through :func:`numpy_op`, which wraps an
``f(const T* in, int64 n, T* out)``-shaped kernel as a numpy->numpy
function.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

__all__ = ["load", "CppExtension", "CUDAExtension", "setup", "numpy_op"]

_CACHE_DIR = os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions")


class _Extension:
    """Handle over a built .so: ``ext.fn_name`` returns the ctypes
    symbol; declare signatures via ``ext.declare``."""

    def __init__(self, path):
        self._path = path
        self._lib = ctypes.CDLL(path)

    def declare(self, name, restype=None, argtypes=()):
        fn = getattr(self._lib, name)
        fn.restype = restype
        fn.argtypes = list(argtypes)
        return fn

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._lib, name)


def load(name, sources, extra_cxx_cflags=None, extra_include_paths=None,
         extra_ldflags=None, build_directory=None, verbose=False):
    """Compile ``sources`` (C++ files) into a cached shared object and
    return an :class:`_Extension` (reference ``cpp_extension.load``)."""
    srcs = [os.fspath(s) for s in sources]
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(extra_cxx_cflags or []).encode())
    tag = h.hexdigest()[:16]
    out_dir = build_directory or _CACHE_DIR
    os.makedirs(out_dir, exist_ok=True)
    out = os.path.join(out_dir, f"{name}_{tag}.so")
    if not os.path.exists(out):
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread"]
        cmd += extra_cxx_cflags or []
        for inc in extra_include_paths or []:
            cmd += ["-I", os.fspath(inc)]
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=out_dir)
        os.close(fd)
        try:
            proc = subprocess.run(cmd + srcs + ["-o", tmp]
                                  + (extra_ldflags or []),
                                  capture_output=True, text=True)
            if proc.returncode != 0:
                raise RuntimeError(
                    f"extension '{name}' failed to build:\n"
                    f"{proc.stderr[-4000:]}")
            os.replace(tmp, out)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        if verbose:
            print(f"built {out}")
    return _Extension(out)


def numpy_op(ext, name, dtype=np.float32):
    """Bind an ``extern "C" void f(const T* in, int64_t n, T* out)``
    symbol as a numpy array -> numpy array function."""
    ct = np.ctypeslib.ndpointer(dtype=dtype, flags="C_CONTIGUOUS")
    fn = ext.declare(name, None, [ct, ctypes.c_int64, ct])

    def call(x):
        x = np.ascontiguousarray(x, dtype=dtype)
        out = np.empty_like(x)
        fn(x.reshape(-1), x.size, out.reshape(-1))
        return out

    call.__name__ = name
    return call


class CppExtension:
    """setuptools-parity descriptor (reference ``CppExtension``)."""

    def __init__(self, sources, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


def CUDAExtension(*args, **kwargs):
    raise NotImplementedError(
        "CUDAExtension has no TPU analog — device kernels are Pallas "
        "(see paddle_tpu/ops/flash_attention.py for the pattern); use "
        "CppExtension/load for host-side C++ ops")


def setup(name=None, ext_modules=None, **kwargs):
    """Eager analog of the reference's setuptools ``setup``: builds each
    CppExtension immediately and returns the handles."""
    exts = []
    for i, ext in enumerate(ext_modules or []):
        exts.append(load(f"{name or 'ext'}_{i}", ext.sources,
                         **ext.kwargs))
    return exts
