"""Reference `python/paddle/utils/lazy_import.py`."""

import importlib


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"module {module_name!r} is required but not "
            "installed (and this build has no network to fetch it)")
