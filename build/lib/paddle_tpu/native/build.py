"""Build + load the native runtime library.

The C++ sources under ``src/`` compile into one shared object cached in
``lib/`` and keyed by a content hash, so the library rebuilds exactly
when a source changes and never otherwise. Reference analog: the cmake
superbuild producing ``core.so`` (`setup.py` → `cmake/`); here the
native surface is small enough that one ``g++ -shared`` call is the
whole build system.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC_DIR = os.path.join(_HERE, "src")
_LIB_DIR = os.path.join(_HERE, "lib")

_lib = None
_lib_err = None


def _sources():
    return sorted(
        os.path.join(_SRC_DIR, f)
        for f in os.listdir(_SRC_DIR)
        if f.endswith(".cc"))


def _content_hash(srcs):
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def build(verbose=False):
    """Compile (if needed) and return the path to the .so.

    Raises ``RuntimeError`` with the compiler output on failure.
    """
    srcs = _sources()
    tag = _content_hash(srcs)
    out = os.path.join(_LIB_DIR, f"_native_{tag}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_LIB_DIR, exist_ok=True)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-Wall", *srcs, "-o", None]
    # build into a temp file then atomically rename, so a concurrent
    # builder (e.g. pytest-xdist workers) never loads a half-written .so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_LIB_DIR)
    os.close(fd)
    cmd[-1] = tmp
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed:\n{proc.stderr[-4000:]}")
        os.replace(tmp, out)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    if verbose:
        print(f"built {out}")
    return out


def load():
    """ctypes.CDLL for the native library, or None if unbuildable.

    Memoized; set ``PADDLE_TPU_DISABLE_NATIVE=1`` to force the pure-
    Python fallbacks.
    """
    global _lib, _lib_err
    if _lib is not None or _lib_err is not None:
        return _lib
    if os.environ.get("PADDLE_TPU_DISABLE_NATIVE") == "1":
        _lib_err = "disabled by PADDLE_TPU_DISABLE_NATIVE"
        return None
    try:
        lib = ctypes.CDLL(build())
    except (RuntimeError, OSError) as e:
        _lib_err = str(e)
        return None
    _declare(lib)
    _lib = lib
    return lib


def load_error():
    return _lib_err


def _declare(lib):
    c = ctypes
    u8p = c.POINTER(c.c_uint8)
    lib.pts_store_server_start.restype = c.c_void_p
    lib.pts_store_server_start.argtypes = [c.c_int]
    lib.pts_store_server_port.restype = c.c_int
    lib.pts_store_server_port.argtypes = [c.c_void_p]
    lib.pts_store_server_stop.restype = None
    lib.pts_store_server_stop.argtypes = [c.c_void_p]
    lib.pts_store_connect.restype = c.c_void_p
    lib.pts_store_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pts_store_disconnect.restype = None
    lib.pts_store_disconnect.argtypes = [c.c_void_p]
    lib.pts_store_set.restype = c.c_int
    lib.pts_store_set.argtypes = [c.c_void_p, c.c_char_p, u8p, c.c_uint64]
    lib.pts_store_get.restype = u8p
    lib.pts_store_get.argtypes = [c.c_void_p, c.c_char_p,
                                  c.POINTER(c.c_uint64), c.c_int64]
    lib.pts_store_add.restype = c.c_int64
    lib.pts_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.pts_store_wait.restype = c.c_int
    lib.pts_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    lib.pts_store_del.restype = c.c_int
    lib.pts_store_del.argtypes = [c.c_void_p, c.c_char_p]
    lib.pts_store_numkeys.restype = c.c_int64
    lib.pts_store_numkeys.argtypes = [c.c_void_p]
    lib.pts_buf_free.restype = None
    lib.pts_buf_free.argtypes = [u8p]

    lib.pts_feed_open.restype = c.c_void_p
    lib.pts_feed_open.argtypes = [c.c_char_p, c.c_uint64, c.c_uint32,
                                  c.c_uint64, c.c_int, c.c_uint64, c.c_int,
                                  c.c_int64]
    lib.pts_feed_batches_per_epoch.restype = c.c_uint64
    lib.pts_feed_batches_per_epoch.argtypes = [c.c_void_p]
    lib.pts_feed_num_samples.restype = c.c_uint64
    lib.pts_feed_num_samples.argtypes = [c.c_void_p]
    lib.pts_feed_next.restype = c.c_int
    lib.pts_feed_next.argtypes = [c.c_void_p, u8p]
    lib.pts_feed_close.restype = None
    lib.pts_feed_close.argtypes = [c.c_void_p]
