// Prefetching token-batch data feed over an mmap'd corpus.
//
// Reference capability: `paddle/fluid/framework/data_feed.cc` (C++ feed
// threads filling per-trainer queues) and the multiprocess DataLoader
// (`python/paddle/io/dataloader/dataloader_iter.py`). TPU-native shape:
// the host's only data-path job is to keep one pinned numpy batch ahead
// of the XLA step, so this is a single mmap + a producer thread filling
// a bounded ring of ready batches — no worker processes, no IPC.
//
// The corpus is a flat binary file of fixed-size samples
// (sample_elems * elem_size bytes each, e.g. packed token ids). Each
// epoch visits every full sample once, optionally mt19937-shuffled with
// a per-epoch seed (seed + epoch), dropping the last partial batch.

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <numeric>
#include <random>
#include <thread>
#include <vector>

namespace {

struct Feed {
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t file_bytes = 0;

  uint64_t sample_bytes = 0;
  uint64_t n_samples = 0;
  uint64_t batch = 0;
  uint64_t batches_per_epoch = 0;
  uint64_t batch_bytes = 0;
  int shuffle = 0;
  uint64_t seed = 0;
  int64_t epochs = 0;  // <= 0: infinite

  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<std::vector<uint8_t>> ready;
  size_t capacity = 4;
  bool done = false;  // producer exhausted all epochs
  std::atomic<bool> stopping{false};
  std::thread producer;

  ~Feed() { close(); }

  void close() {
    bool expected = false;
    if (!stopping.compare_exchange_strong(expected, true)) return;
    {
      std::lock_guard<std::mutex> lk(mu);
      cv_put.notify_all();
      cv_get.notify_all();
    }
    if (producer.joinable()) producer.join();
    if (base) ::munmap(const_cast<uint8_t*>(base), file_bytes);
    if (fd >= 0) ::close(fd);
  }

  void produce() {
    std::vector<uint64_t> order(n_samples);
    for (int64_t epoch = 0; epochs <= 0 || epoch < epochs; ++epoch) {
      // fresh iota each epoch so the permutation is a pure function of
      // (seed, epoch) — a resumed job replays the original data order
      std::iota(order.begin(), order.end(), 0);
      if (shuffle) {
        std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch));
        std::shuffle(order.begin(), order.end(), rng);
      }
      for (uint64_t b = 0; b < batches_per_epoch; ++b) {
        std::vector<uint8_t> buf(batch_bytes);
        for (uint64_t i = 0; i < batch; ++i) {
          uint64_t s = order[b * batch + i];
          std::memcpy(buf.data() + i * sample_bytes,
                      base + s * sample_bytes, sample_bytes);
        }
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [this] {
          return stopping.load() || ready.size() < capacity;
        });
        if (stopping.load()) return;
        ready.push_back(std::move(buf));
        cv_get.notify_one();
      }
    }
    std::lock_guard<std::mutex> lk(mu);
    done = true;
    cv_get.notify_all();
  }

  bool start(const char* path) {
    fd = ::open(path, O_RDONLY);
    if (fd < 0) return false;
    struct stat st{};
    if (::fstat(fd, &st) != 0 || st.st_size <= 0) return false;
    file_bytes = static_cast<size_t>(st.st_size);
    void* m = ::mmap(nullptr, file_bytes, PROT_READ, MAP_PRIVATE, fd, 0);
    if (m == MAP_FAILED) return false;
    ::madvise(m, file_bytes, MADV_WILLNEED);
    base = static_cast<const uint8_t*>(m);
    n_samples = file_bytes / sample_bytes;
    batches_per_epoch = n_samples / batch;
    batch_bytes = batch * sample_bytes;
    if (batches_per_epoch == 0) return false;
    producer = std::thread([this] { produce(); });
    return true;
  }
};

}  // namespace

extern "C" {

void* pts_feed_open(const char* path, uint64_t sample_elems,
                    uint32_t elem_size, uint64_t batch, int shuffle,
                    uint64_t seed, int prefetch_depth, int64_t epochs) {
  auto* f = new Feed();
  f->sample_bytes = sample_elems * elem_size;
  f->batch = batch;
  f->shuffle = shuffle;
  f->seed = seed;
  f->capacity = prefetch_depth > 0 ? static_cast<size_t>(prefetch_depth) : 4;
  f->epochs = epochs;
  if (f->sample_bytes == 0 || batch == 0 || !f->start(path)) {
    delete f;
    return nullptr;
  }
  return f;
}

uint64_t pts_feed_batches_per_epoch(void* h) {
  return static_cast<Feed*>(h)->batches_per_epoch;
}

uint64_t pts_feed_num_samples(void* h) {
  return static_cast<Feed*>(h)->n_samples;
}

// Blocks until the next batch is ready and copies it into dst
// (batch * sample_elems * elem_size bytes). Returns 0 on success, -1
// when the feed is exhausted or closed.
int pts_feed_next(void* h, uint8_t* dst) {
  auto* f = static_cast<Feed*>(h);
  std::unique_lock<std::mutex> lk(f->mu);
  f->cv_get.wait(lk, [f] {
    return f->stopping.load() || f->done || !f->ready.empty();
  });
  if (f->ready.empty()) return -1;
  std::vector<uint8_t> buf = std::move(f->ready.front());
  f->ready.pop_front();
  f->cv_put.notify_one();
  lk.unlock();
  std::memcpy(dst, buf.data(), buf.size());
  return 0;
}

void pts_feed_close(void* h) { delete static_cast<Feed*>(h); }

}  // extern "C"
