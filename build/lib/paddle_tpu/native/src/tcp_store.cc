// TCPStore: rendezvous key-value store for multi-host bootstrap.
//
// Reference capability: `paddle/phi/core/distributed/store/tcp_store.h:121`
// (TCPStore : Store — master on rank 0, blocking get/wait, atomic add)
// and `tcp_utils.cc`. This is an original C++ implementation shaped for
// the TPU control plane: the data plane needs no process groups (GSPMD
// emits ICI/DCN collectives), so all that is left is a small, reliable
// bootstrap/rendezvous store — set/get/add/wait/delete over TCP with
// blocking semantics served by a thread-per-connection master.
//
// Wire protocol (little-endian):
//   request:  [u8 cmd][u32 klen][key][u64 vlen][value]
//             cmd: 1=SET 2=GET 3=ADD 4=WAIT 5=DEL 6=NUMKEYS
//             GET/WAIT: vlen==8, value = i64 timeout_ms
//             ADD:      vlen==8, value = i64 delta
//   response: [u8 status][u64 vlen][value]   status: 1=ok 0=timeout/miss

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <netdb.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

enum Cmd : uint8_t { kSet = 1, kGet = 2, kAdd = 3, kWait = 4, kDel = 5,
                     kNumKeys = 6 };

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  std::thread accept_thread;
  // detached handler threads are tracked by fd + active count so stop()
  // can interrupt their blocking recv (shutdown) and wait for drain
  std::mutex conn_mu;
  std::condition_variable conn_cv;
  std::unordered_set<int> open_fds;
  int active = 0;

  std::mutex mu;
  std::condition_variable cv;  // signalled on every SET/ADD/DEL
  std::unordered_map<std::string, std::vector<uint8_t>> data;

  ~Server() { stop(); }

  void stop() {
    bool expected = false;
    if (!stopping.compare_exchange_strong(expected, true)) return;
    if (listen_fd >= 0) {
      ::shutdown(listen_fd, SHUT_RDWR);
      ::close(listen_fd);
    }
    {
      // Hold mu so the stopping publish is ordered against handlers'
      // predicate checks: notify without it can slip between a waiter
      // evaluating the predicate and parking, losing the wakeup.
      std::lock_guard<std::mutex> lk(mu);
      cv.notify_all();  // release handlers parked in blocking GET/WAIT
    }
    {
      std::lock_guard<std::mutex> lk(conn_mu);
      for (int fd : open_fds) ::shutdown(fd, SHUT_RDWR);
    }
    if (accept_thread.joinable()) accept_thread.join();
    std::unique_lock<std::mutex> lk(conn_mu);
    conn_cv.wait(lk, [this] { return active == 0; });
  }

  void conn_main(int fd) {
    handle_conn(fd);
    std::lock_guard<std::mutex> lk(conn_mu);
    open_fds.erase(fd);
    ::close(fd);  // after erase: stop() can no longer shutdown this fd
    --active;
    conn_cv.notify_all();
  }

  void handle_conn(int fd) {
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    for (;;) {
      uint8_t cmd;
      uint32_t klen;
      uint64_t vlen;
      if (!recv_all(fd, &cmd, 1) || !recv_all(fd, &klen, 4)) break;
      if (klen > (1u << 20)) break;
      std::string key(klen, '\0');
      if (klen && !recv_all(fd, &key[0], klen)) break;
      if (!recv_all(fd, &vlen, 8)) break;
      if (vlen > (1ull << 32)) break;
      std::vector<uint8_t> val(vlen);
      if (vlen && !recv_all(fd, val.data(), vlen)) break;

      uint8_t status = 1;
      std::vector<uint8_t> reply;
      switch (cmd) {
        case kSet: {
          std::lock_guard<std::mutex> lk(mu);
          data[key] = std::move(val);
          cv.notify_all();
          break;
        }
        case kAdd: {
          int64_t delta = 0;
          if (val.size() == 8) std::memcpy(&delta, val.data(), 8);
          std::lock_guard<std::mutex> lk(mu);
          int64_t cur = 0;
          auto it = data.find(key);
          if (it != data.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::vector<uint8_t> stored(8);
          std::memcpy(stored.data(), &cur, 8);
          data[key] = stored;
          reply = stored;
          cv.notify_all();
          break;
        }
        case kGet:
        case kWait: {
          int64_t timeout_ms = -1;
          if (val.size() == 8) std::memcpy(&timeout_ms, val.data(), 8);
          std::unique_lock<std::mutex> lk(mu);
          auto ready = [&] {
            return stopping.load() || data.count(key) != 0;
          };
          if (timeout_ms < 0) {
            cv.wait(lk, ready);
          } else {
            cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), ready);
          }
          auto it = data.find(key);
          if (it == data.end()) {
            status = 0;  // timeout (or server stopping)
          } else if (cmd == kGet) {
            reply = it->second;
          }
          break;
        }
        case kDel: {
          std::lock_guard<std::mutex> lk(mu);
          status = data.erase(key) ? 1 : 0;
          cv.notify_all();
          break;
        }
        case kNumKeys: {
          std::lock_guard<std::mutex> lk(mu);
          int64_t n = static_cast<int64_t>(data.size());
          reply.resize(8);
          std::memcpy(reply.data(), &n, 8);
          break;
        }
        default:
          status = 0;
      }
      uint64_t rlen = reply.size();
      if (!send_all(fd, &status, 1) || !send_all(fd, &rlen, 8) ||
          (rlen && !send_all(fd, reply.data(), rlen)))
        break;
    }
  }

  bool start(int want_port) {
    listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(want_port));
    if (::bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
               sizeof(addr)) != 0 ||
        ::listen(listen_fd, 128) != 0) {
      ::close(listen_fd);
      listen_fd = -1;
      return false;
    }
    socklen_t alen = sizeof(addr);
    ::getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
    port = ntohs(addr.sin_port);
    accept_thread = std::thread([this] {
      for (;;) {
        int fd = ::accept(listen_fd, nullptr, nullptr);
        if (fd < 0) {
          if (stopping.load()) return;
          continue;
        }
        {
          std::lock_guard<std::mutex> lk(conn_mu);
          if (stopping.load()) {
            ::close(fd);
            continue;
          }
          open_fds.insert(fd);
          ++active;
        }
        std::thread([this, fd] { conn_main(fd); }).detach();
      }
    });
    return true;
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;  // one in-flight request per connection

  ~Client() {
    if (fd >= 0) ::close(fd);
  }

  // Single request-response round trip; returns status (or -1 on I/O
  // error) and fills *out.
  int request(uint8_t cmd, const char* key, const void* val, uint64_t vlen,
              std::vector<uint8_t>* out) {
    std::lock_guard<std::mutex> lk(mu);
    uint32_t klen = static_cast<uint32_t>(std::strlen(key));
    if (!send_all(fd, &cmd, 1) || !send_all(fd, &klen, 4) ||
        !send_all(fd, key, klen) || !send_all(fd, &vlen, 8) ||
        (vlen && !send_all(fd, val, vlen)))
      return -1;
    uint8_t status;
    uint64_t rlen;
    if (!recv_all(fd, &status, 1) || !recv_all(fd, &rlen, 8)) return -1;
    out->resize(rlen);
    if (rlen && !recv_all(fd, out->data(), rlen)) return -1;
    return status;
  }
};

}  // namespace

extern "C" {

void* pts_store_server_start(int port) {
  auto* s = new Server();
  if (!s->start(port)) {
    delete s;
    return nullptr;
  }
  return s;
}

int pts_store_server_port(void* h) { return static_cast<Server*>(h)->port; }

void pts_store_server_stop(void* h) { delete static_cast<Server*>(h); }

void* pts_store_connect(const char* host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  std::string port_str = std::to_string(port);
  for (;;) {
    addrinfo* res = nullptr;  // re-resolve per retry: DNS may lag boot
    if (::getaddrinfo(host, port_str.c_str(), &hints, &res) == 0) {
      for (addrinfo* ai = res; ai; ai = ai->ai_next) {
        int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
          int one = 1;
          ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          ::freeaddrinfo(res);
          auto* c = new Client();
          c->fd = fd;
          return c;
        }
        ::close(fd);
      }
      ::freeaddrinfo(res);
    }
    if (std::chrono::steady_clock::now() >= deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void pts_store_disconnect(void* h) { delete static_cast<Client*>(h); }

int pts_store_set(void* h, const char* key, const uint8_t* val,
                  uint64_t len) {
  std::vector<uint8_t> out;
  return static_cast<Client*>(h)->request(kSet, key, val, len, &out) == 1
             ? 0
             : -1;
}

// Returns a malloc'd buffer the caller frees with pts_buf_free; *len set
// to the value size. nullptr on timeout / error.
uint8_t* pts_store_get(void* h, const char* key, uint64_t* len,
                       int64_t timeout_ms) {
  std::vector<uint8_t> out;
  int st = static_cast<Client*>(h)->request(kGet, key, &timeout_ms, 8, &out);
  if (st != 1) return nullptr;
  auto* buf = static_cast<uint8_t*>(std::malloc(out.size() ? out.size() : 1));
  if (!out.empty()) std::memcpy(buf, out.data(), out.size());
  *len = out.size();
  return buf;
}

int64_t pts_store_add(void* h, const char* key, int64_t delta) {
  std::vector<uint8_t> out;
  int st = static_cast<Client*>(h)->request(kAdd, key, &delta, 8, &out);
  if (st != 1 || out.size() != 8) return INT64_MIN;
  int64_t v;
  std::memcpy(&v, out.data(), 8);
  return v;
}

int pts_store_wait(void* h, const char* key, int64_t timeout_ms) {
  std::vector<uint8_t> out;
  int st = static_cast<Client*>(h)->request(kWait, key, &timeout_ms, 8, &out);
  return st == 1 ? 0 : -1;
}

int pts_store_del(void* h, const char* key) {
  std::vector<uint8_t> out;
  return static_cast<Client*>(h)->request(kDel, key, nullptr, 0, &out) == 1
             ? 0
             : -1;
}

int64_t pts_store_numkeys(void* h) {
  std::vector<uint8_t> out;
  int st = static_cast<Client*>(h)->request(kNumKeys, "", nullptr, 0, &out);
  if (st != 1 || out.size() != 8) return -1;
  int64_t v;
  std::memcpy(&v, out.data(), 8);
  return v;
}

void pts_buf_free(uint8_t* p) { std::free(p); }

}  // extern "C"
