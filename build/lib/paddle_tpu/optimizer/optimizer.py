"""Optimizer base class.

Reference: `python/paddle/optimizer/optimizer.py:104` (``Optimizer``:
accumulator creation, grad clip + regularization hooks, ``step`` /
``clear_grad`` / ``state_dict``). TPU-native design: the whole update is
pure jnp on the Tensor payloads — under ``paddle_tpu.jit`` tracing the
entire ``opt.step()`` folds into the one compiled XLA computation, with
optimizer state as donated inputs. The learning rate enters as a scalar
(host value or scheduler output) so lr changes never retrace.
"""

from __future__ import annotations

import collections

import jax.numpy as jnp

from ..framework.tensor import Tensor, Parameter, no_grad
from ..framework import dtype as dtypes
from . import lr as lr_mod

__all__ = ["Optimizer"]

_LOW_PRECISION = ("bfloat16", "float16")


class Optimizer:
    """Base optimizer. Subclasses implement ``_create_accumulators`` and
    ``_single_update(p, g, lr)`` returning the new parameter value (and
    updating accumulators via ``_set_accumulator``)."""

    _accum_names: tuple = ()

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        if parameters is None:
            raise ValueError(
                "parameters is required (eager mode): pass model.parameters()")
        self._parameter_list = []
        self._param_groups = []
        plist = list(parameters)
        if plist and isinstance(plist[0], dict):
            for group in plist:
                g = dict(group)
                g["params"] = list(g["params"])
                self._param_groups.append(g)
                self._parameter_list.extend(g["params"])
        else:
            self._param_groups.append({"params": plist})
            self._parameter_list = plist
        self._learning_rate = learning_rate
        self._lr_override = None   # traced scalar injected by paddle_tpu.jit
        self.regularization = weight_decay
        self._group_weight_decay = None  # set per-group during step()
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._name = name or type(self).__name__.lower()
        # accumulators: name -> {id(param): Tensor}
        self._accumulators = collections.defaultdict(dict)
        self._accumulators_created = False
        self._param_names = {}
        for i, p in enumerate(self._parameter_list):
            self._param_names[id(p)] = p.name or f"param_{i}"

    # -- learning rate ------------------------------------------------------
    def get_lr(self):
        if self._lr_override is not None:
            return self._lr_override
        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the learning rate is an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        if not isinstance(scheduler, lr_mod.LRScheduler):
            raise TypeError("expected an LRScheduler")
        self._learning_rate = scheduler

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if id(param) in self._accumulators[name]:
            return self._accumulators[name][id(param)]
        shape = shape if shape is not None else param._data.shape
        dt = dtypes.convert_dtype(dtype) if dtype is not None else param._data.dtype
        if self._multi_precision and str(param.dtype) in _LOW_PRECISION \
                and dtype is None:
            dt = jnp.float32
        t = Tensor(jnp.full(shape, fill_value, dtype=dt), stop_gradient=True)
        t.name = f"{self._param_names[id(param)]}_{name}"
        self._accumulators[name][id(param)] = t
        return t

    def _get_accumulator(self, name, param):
        try:
            return self._accumulators[name][id(param)]
        except KeyError:
            raise RuntimeError(
                f"accumulator {name!r} for parameter "
                f"{self._param_names.get(id(param))} not created yet")

    def _set_accumulator(self, name, param, value):
        acc = self._accumulators[name][id(param)]
        acc._data = value if not isinstance(value, Tensor) else value._data

    def _master_weight(self, param):
        """fp32 master copy for low-precision params (reference:
        optimizer.py _create_master_weight)."""
        if not (self._multi_precision and str(param.dtype) in _LOW_PRECISION):
            return None
        if id(param) not in self._accumulators["master_weight"]:
            t = Tensor(param._data.astype(jnp.float32), stop_gradient=True)
            t.name = f"{self._param_names[id(param)]}_master_weight"
            self._accumulators["master_weight"][id(param)] = t
        return self._accumulators["master_weight"][id(param)]

    def _create_accumulators(self, params):
        for name in self._accum_names:
            for p in params:
                self._add_accumulator(name, p)

    # -- the update ---------------------------------------------------------
    def _apply_regularization(self, p, g):
        """L2 regularization folded into the gradient (reference:
        ``append_regularization_ops``). Param-level regularizer wins over
        the group-level one, which wins over the optimizer-level one
        (reference optimizer.py:1918 sets param.regularizer from the group)."""
        if getattr(p, "regularizer", None) is not None:
            reg = p.regularizer
        elif self._group_weight_decay is not None:
            reg = self._group_weight_decay
        else:
            reg = self.regularization
        if reg is None:
            return g
        coeff = getattr(reg, "coeff", None)
        if coeff is None:  # plain float weight_decay == L2Decay
            coeff = float(reg)
        if getattr(reg, "_l1", False):
            return g + coeff * jnp.sign(p._data).astype(g.dtype)
        return g + jnp.asarray(coeff, g.dtype) * p._data.astype(g.dtype)

    @no_grad()
    def step(self):
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if p.trainable and p.grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        # _add_accumulator is idempotent — run every step so params whose
        # grads first appear later (staged unfreezing) get their state
        self._create_accumulators([p for p, _ in params_grads])
        self._accumulators_created = True
        for group in self._param_groups:
            group_lr_scale = group.get("learning_rate", 1.0)
            self._group_weight_decay = group.get("weight_decay")
            group_params = {id(p) for p in group["params"]}
            for p, g in params_grads:
                if id(p) not in group_params:
                    continue
                lr = self.get_lr() * group_lr_scale \
                    * p.optimize_attr.get("learning_rate", 1.0)
                garr = g._data if isinstance(g, Tensor) else g
                master = self._master_weight(p)
                if master is not None:
                    new_master = self._single_update(
                        p, self._apply_regularization(
                            p, garr.astype(jnp.float32)), lr,
                        value=master._data)
                    master._data = new_master
                    p._data = new_master.astype(p._data.dtype)
                else:
                    garr = self._apply_regularization(p, garr.astype(p._data.dtype))
                    p._data = self._single_update(p, garr, lr, value=p._data)

    def _single_update(self, p, g, lr, value):
        raise NotImplementedError

    minimize_step = step

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Reference ``Optimizer.minimize``: backward + step."""
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list]

    # -- bookkeeping --------------------------------------------------------
    @no_grad()
    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        """Accumulators keyed by '{param_name}_{acc_name}' (reference:
        optimizer.py state_dict), plus scheduler state under 'LR_Scheduler'."""
        state = {}
        for name, per_param in self._accumulators.items():
            for pid, acc in per_param.items():
                state[acc.name] = acc
        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            state["LR_Scheduler"] = self._learning_rate.state_dict()
        return state

    def set_state_dict(self, state_dict):
        state_dict = dict(state_dict)
        sched = state_dict.pop("LR_Scheduler", None)
        if sched is not None and isinstance(self._learning_rate,
                                            lr_mod.LRScheduler):
            self._learning_rate.set_state_dict(sched)
        if not self._accumulators_created:
            self._create_accumulators(
                [p for p in self._parameter_list if p.trainable])
            self._accumulators_created = True
        for name, per_param in self._accumulators.items():
            for pid, acc in per_param.items():
                if acc.name in state_dict:
                    v = state_dict[acc.name]
                    acc._data = jnp.asarray(
                        v._data if isinstance(v, Tensor) else v,
                        dtype=acc._data.dtype)

    def _accumulator_pytree(self):
        """(names, list-of-lists of Tensors) for jit capture — a stable
        flattening of all optimizer state."""
        out = []
        for name in sorted(self._accumulators):
            for pid in self._accumulators[name]:
                out.append(self._accumulators[name][pid])
        return out
